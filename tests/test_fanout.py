"""Distributed query fan-out: partial-aggregate pushdown + scatter-gather.

In-process cluster topology (real sockets, like test_distributed.py):
ingest-mode servers serve the pushdown endpoint; a query-mode Parseable
scatters to them. Covers the acceptance invariants: an all-pushdown
aggregate transfers ZERO raw staging rows, unsupported plans / 404ing /
erroring peers fall back to central pull with identical results, and
hedged or dead peers never produce duplicate or dropped groups.
"""

import asyncio
import base64
import threading
import time

import pyarrow as pa
import pytest

from parseable_tpu.config import Mode, Options, StorageOptions
from parseable_tpu.core import Parseable
from parseable_tpu.query.session import QuerySession
from parseable_tpu.server import cluster as C
from parseable_tpu.server.app import ServerState, build_app

AUTH = {"Authorization": "Basic " + base64.b64encode(b"admin:admin").decode()}

SQL = (
    "SELECT host, count(*) c, sum(v) s, avg(v) a, min(v) mn, max(v) mx "
    "FROM dist GROUP BY host ORDER BY host"
)


@pytest.fixture(autouse=True)
def _fresh_cluster_state():
    C._dead_nodes.clear()
    C._rr_index = 0
    yield
    C._dead_nodes.clear()


def make_parseable(tmp_path, node: str, mode: Mode) -> Parseable:
    opts = Options()
    opts.mode = mode
    opts.local_staging_path = tmp_path / f"staging-{node}"
    storage = StorageOptions(backend="local-store", root=tmp_path / "shared-store")
    return Parseable(opts, storage)


def run(coro):
    asyncio.new_event_loop().run_until_complete(coro)


async def boot_ingestors(tmp_path, n=2, stream="dist", rows_per_node=10, prefix="ing"):
    """N ingest-mode servers on real ports, each holding `rows_per_node`
    staging rows for `stream`. `prefix` keeps staging dirs (and with them
    the persisted node identities) distinct across separate boots."""
    import aiohttp
    from aiohttp.test_utils import TestServer

    states, servers = [], []
    for i in range(n):
        p = make_parseable(tmp_path, f"{prefix}{i}", Mode.INGEST)
        state = ServerState(p)
        server = TestServer(build_app(state))
        await server.start_server()
        p.register_node(f"127.0.0.1:{server.port}")
        states.append(state)
        servers.append(server)
    async with aiohttp.ClientSession() as http:
        for i, server in enumerate(servers):
            url = f"http://127.0.0.1:{server.port}/api/v1/ingest"
            rows = [{"host": f"node{i}", "v": float(j)} for j in range(rows_per_node)]
            async with http.post(
                url, json=rows, headers={**AUTH, "X-P-Stream": stream}
            ) as resp:
                assert resp.status == 200, await resp.text()
    return states, servers


async def teardown(states, servers):
    for s in servers:
        await s.close()
    for st in states:
        st.stop()  # full pool shutdown (psan-thread-leak), not just the flag


def query_on(tmp_path, node: str, sql: str = SQL, pushdown: bool = True, **opt_overrides):
    q = make_parseable(tmp_path, node, Mode.QUERY)
    try:
        q.options.query_pushdown = pushdown
        for k, v in opt_overrides.items():
            setattr(q.options, k, v)
        res = QuerySession(q, engine="cpu").query(sql)
        return res.to_json_rows(), res.stats
    finally:
        q.shutdown()  # pools must not outlive the test (psan-thread-leak)


EXPECTED = [
    {"host": "node0", "c": 10, "s": 45.0, "a": 4.5, "mn": 0.0, "mx": 9.0},
    {"host": "node1", "c": 10, "s": 45.0, "a": 4.5, "mn": 0.0, "mx": 9.0},
]


# ---------------------------------------------------------------- pushdown


def test_pushdown_zero_raw_staging_rows(tmp_path, monkeypatch):
    """An aggregate whose peers all support pushdown transfers ZERO raw
    staging rows: the querier-side fetch never runs AND the peers'
    instrumented staging endpoint is never hit."""
    from parseable_tpu.server import app as A

    staging_hits = []
    orig_staging = A.internal_staging

    async def counting_staging(request):
        staging_hits.append(request.path)
        return await orig_staging(request)

    monkeypatch.setattr(A, "internal_staging", counting_staging)

    fetches = []
    orig_fetch = C._fetch_one

    def counting_fetch(*args, **kwargs):
        fetches.append(args)
        return orig_fetch(*args, **kwargs)

    monkeypatch.setattr(C, "_fetch_one", counting_fetch)

    async def scenario():
        states, servers = await boot_ingestors(tmp_path)
        # one node also uploads: its owned manifests must be delegated too
        states[0].p.local_sync(shutdown=True)
        states[0].p.sync_all_streams()
        rows, stats = await asyncio.get_running_loop().run_in_executor(
            None, query_on, tmp_path, "q"
        )
        assert rows == EXPECTED
        fan = stats["stages"]["fanout"]
        assert fan["mode"] == "pushdown"
        assert fan["ok"] == 2 and fan["fallback"] == 0
        assert fan["bytes"] > 0
        assert fan["files_delegated"] >= 1  # node0's uploaded parquet
        assert fetches == [], "querier pulled raw staging despite pushdown"
        assert staging_hits == [], "a peer served raw staging despite pushdown"
        # the peers' scan accounting rode back on the response headers
        assert stats["rows_scanned"] >= 20
        await teardown(states, servers)

    run(scenario())


def test_pushdown_parity_with_central(tmp_path):
    """Pushdown and central pull agree exactly — including avg and stddev,
    which are only mergeable because the wire carries partial state."""
    sql = (
        "SELECT host, count(*) c, sum(v) s, avg(v) a, stddev(v) sd "
        "FROM dist GROUP BY host ORDER BY host"
    )

    async def scenario():
        states, servers = await boot_ingestors(tmp_path)
        states[0].p.local_sync(shutdown=True)
        states[0].p.sync_all_streams()

        def both():
            pushed, pstats = query_on(tmp_path, "qa", sql, pushdown=True)
            central, cstats = query_on(tmp_path, "qb", sql, pushdown=False)
            return pushed, pstats, central, cstats

        pushed, pstats, central, cstats = await asyncio.get_running_loop().run_in_executor(
            None, both
        )
        assert pstats["stages"]["fanout"]["ok"] == 2
        assert cstats["stages"]["fanout"]["mode"] == "central"
        assert cstats["stages"]["fanout"]["fanin_bytes"] > 0
        assert len(pushed) == len(central) == 2
        for pr, cr in zip(pushed, central):
            assert pr["host"] == cr["host"] and pr["c"] == cr["c"]
            for k in ("s", "a", "sd"):
                assert pr[k] == pytest.approx(cr[k], rel=1e-9)
        await teardown(states, servers)

    run(scenario())


def test_staged_parquet_visible_before_upload(tmp_path):
    """Conservation across the staging lifecycle: rows flushed to staging
    parquet but not yet uploaded/committed must stay queryable — via the
    peer's pushed-down partial AND via the central staging fan-in — and
    must not double-count once the upload commits them to the manifest.
    (Regression: the peer partial skipped staged parquet while the querier
    had delegated the whole slice, so those rows vanished for a full
    upload interval.)"""

    async def scenario():
        states, servers = await boot_ingestors(tmp_path)
        # flush node0's arrows to staging parquet WITHOUT uploading: the
        # rows now exist only as flushed-but-uncommitted parquet
        states[0].p.local_sync(shutdown=True)
        assert states[0].p.streams.get("dist").parquet_files()

        def both():
            pushed, pstats = query_on(tmp_path, "qsp", pushdown=True)
            central, cstats = query_on(tmp_path, "qsc", pushdown=False)
            return pushed, pstats, central, cstats

        loop = asyncio.get_running_loop()
        pushed, pstats, central, cstats = await loop.run_in_executor(None, both)
        assert pstats["stages"]["fanout"]["ok"] == 2
        assert pushed == EXPECTED
        assert cstats["stages"]["fanout"]["mode"] == "central"
        assert central == EXPECTED

        # commit the staged parquet; books must still balance (no doubles)
        states[0].p.sync_all_streams()
        pushed, pstats, central, _ = await loop.run_in_executor(None, both)
        assert pstats["stages"]["fanout"]["ok"] == 2
        assert pushed == EXPECTED
        assert central == EXPECTED
        await teardown(states, servers)

    run(scenario())


def test_committed_staged_copy_not_double_counted(tmp_path):
    """The commit -> unlink window: a staged parquet whose basename is
    already in the manifest (upload committed, local copy still on disk)
    must be served by the manifest scan only — the peer partial skips the
    lingering copy."""
    import shutil

    from parseable_tpu.query import fanout as FO

    async def scenario():
        states, servers = await boot_ingestors(tmp_path, n=1)
        p = states[0].p
        p.local_sync(shutdown=True)
        p.sync_all_streams()  # upload + commit + unlink
        stream = p.streams.get("dist")
        assert stream.parquet_files() == []
        # resurrect the committed file in staging, as if unlink hadn't
        # happened yet
        store = tmp_path / "shared-store"
        committed = [f for f in store.rglob("*.parquet") if "dist" in str(f)]
        assert committed
        for f in committed:
            shutil.copy2(f, stream.data_path / f.name)

        def partial():
            return FO.execute_local_partial(p, "dist", SQL, None, None)

        out = await asyncio.get_running_loop().run_in_executor(None, partial)
        assert out is not None
        payload, meta = out
        assert meta["rows_scanned"] == 10, meta  # 20 would mean a double count
        table = FO.deserialize_table(payload)
        # one partial row per group, carrying a count partial of 10 total
        assert table.num_rows == 1
        await teardown(states, servers)

    run(scenario())


def test_unsupported_plan_stays_central(tmp_path, monkeypatch):
    """A plan the partial protocol can't express (no GROUP BY) never
    scatters — it uses the bounded central pull."""
    partial_hits = []
    from parseable_tpu.server import app as A

    orig = A.internal_query_partial

    async def counting(request):
        partial_hits.append(request.path)
        return await orig(request)

    monkeypatch.setattr(A, "internal_query_partial", counting)

    async def scenario():
        states, servers = await boot_ingestors(tmp_path)
        rows, stats = await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: query_on(tmp_path, "q", "SELECT count(*) c FROM dist WHERE v >= 0"),
        )
        assert rows[0]["c"] == 20
        assert partial_hits == []
        await teardown(states, servers)

    run(scenario())


def test_peer_404_falls_back_with_identical_results(tmp_path, monkeypatch):
    """A peer running an older build (no partial endpoint -> 404) is served
    by central pull for exactly its slice; results match the all-central
    answer."""
    from parseable_tpu.server import app as A

    real_partial = A.internal_query_partial

    async def legacy_partial(request):
        return A.web.json_response({"error": "no such route"}, status=404)

    async def scenario():
        # first peer is legacy: build its app with the 404 stub
        monkeypatch.setattr(A, "internal_query_partial", legacy_partial)
        states0, servers0 = await boot_ingestors(tmp_path, n=1, prefix="legacy")
        monkeypatch.setattr(A, "internal_query_partial", real_partial)
        states1, servers1 = await boot_ingestors(tmp_path, n=1)
        # distinct host on the modern peer so the groups differ per node
        import aiohttp

        async with aiohttp.ClientSession() as http:
            url = f"http://127.0.0.1:{servers1[0].port}/api/v1/ingest"
            async with http.post(
                url,
                json=[{"host": "node1", "v": float(j)} for j in range(10)],
                headers={**AUTH, "X-P-Stream": "dist"},
            ) as resp:
                assert resp.status == 200

        def both():
            pushed, pstats = query_on(tmp_path, "qa")
            central, _ = query_on(tmp_path, "qb", pushdown=False)
            return pushed, pstats, central

        pushed, pstats, central = await asyncio.get_running_loop().run_in_executor(
            None, both
        )
        fan = pstats["stages"]["fanout"]
        assert fan["fallback"] == 1 and fan["ok"] == 1
        assert [r["result"] for r in fan["per_peer"].values()].count("http_404") == 1
        assert fan["fanin_bytes"] > 0  # the legacy peer's staging was pulled
        assert pushed == central
        await teardown(states0 + states1, servers0 + servers1)

    run(scenario())


def test_hedged_slow_peer_no_duplicate_or_dropped_groups(tmp_path, monkeypatch):
    """A peer that answers slowly gets a hedged duplicate request; exactly
    one of the two answers merges (counts stay exact), the other is
    discarded."""
    from parseable_tpu.server import app as A

    orig = A.internal_query_partial
    calls = []

    async def slow_once(request):
        calls.append(time.monotonic())
        if len(calls) == 1:
            await asyncio.sleep(1.0)
        return await orig(request)

    monkeypatch.setattr(A, "internal_query_partial", slow_once)

    async def scenario():
        states, servers = await boot_ingestors(tmp_path, n=1)

        rows, stats = await asyncio.get_running_loop().run_in_executor(
            None, lambda: query_on(tmp_path, "q", fanout_hedge_ms=120)
        )
        # duplicate merges would double c/s; drops would lose the group
        assert rows == [EXPECTED[0]]
        fan = stats["stages"]["fanout"]
        assert fan["hedged"] >= 1
        assert fan["ok"] == 1 and fan["fallback"] == 0
        assert len(calls) >= 2, "hedge request never fired"
        await teardown(states, servers)

    run(scenario())


def test_erroring_and_dead_peers_fall_back_without_dupes_or_drops(tmp_path):
    """Merge parity with an injected always-500 peer (reachable, failing
    pushdown — its slice is recovered over central pull) and an injected
    dead peer (nothing listens — skipped by liveness everywhere, exactly
    like the central path)."""
    from aiohttp import web
    from aiohttp.test_utils import TestServer

    import pyarrow.ipc as ipc
    import io

    async def scenario():
        states, servers = await boot_ingestors(tmp_path, n=1)
        p0 = states[0].p

        # reachable fake peer: live, owns nothing, 500s pushdown, serves
        # 5 staging rows over the raw data plane
        fake_rows = pa.table({"host": ["fake"] * 5, "v": [2.0] * 5})

        async def liveness(request):
            return web.Response(status=200)

        async def partial(request):
            return web.json_response({"error": "boom"}, status=500)

        async def staging(request):
            sink = io.BytesIO()
            with ipc.new_stream(sink, fake_rows.schema) as w:
                w.write_table(fake_rows)
            return web.Response(body=sink.getvalue())

        fake_app = web.Application()
        fake_app.router.add_get("/api/v1/liveness", liveness)
        fake_app.router.add_post(
            "/api/v1/internal/query/partial/{name}", partial
        )
        fake_app.router.add_get("/api/v1/internal/staging/{name}", staging)
        fake_server = TestServer(fake_app)
        await fake_server.start_server()
        p0.metastore.put_node(
            {
                "node_id": "fakenode",
                "node_type": "ingestor",
                "domain_name": f"http://127.0.0.1:{fake_server.port}",
                "owner_tag": "fakehost-no-such-prefix.",
            }
        )
        # dead peer: registered but nothing listens
        p0.metastore.put_node(
            {
                "node_id": "deadnode",
                "node_type": "ingestor",
                "domain_name": "http://127.0.0.1:1",
                "owner_tag": "deadhost-no-such-prefix.",
            }
        )

        def both():
            pushed, pstats = query_on(tmp_path, "qa", fanout_timeout_ms=3000)
            C._dead_nodes.clear()  # independent probe state for the A/B
            central, _ = query_on(tmp_path, "qb", pushdown=False)
            return pushed, pstats, central

        pushed, pstats, central = await asyncio.get_running_loop().run_in_executor(
            None, both
        )
        fan = pstats["stages"]["fanout"]
        # real peer ok; fake peer retried once, then fell back
        assert fan["ok"] == 1 and fan["fallback"] == 1 and fan["retries"] == 1
        # the fake peer's 5 staging rows arrived via fallback, once
        assert {"host": "fake", "c": 5, "s": 10.0, "a": 2.0, "mn": 2.0, "mx": 2.0} in pushed
        assert pushed == central
        await fake_server.close()
        await teardown(states, servers)

    run(scenario())


# ------------------------------------------------------- bounded fan-in


def test_internal_staging_bounds_and_projection(tmp_path):
    """The staging endpoint filters to [start, end) and projects columns
    (timestamp always included) before serializing."""
    import aiohttp
    import pyarrow.ipc as ipc
    import io

    async def scenario():
        states, servers = await boot_ingestors(tmp_path, n=1)
        base = f"http://127.0.0.1:{servers[0].port}/api/v1/internal/staging/dist"
        async with aiohttp.ClientSession() as http:
            # full window
            async with http.get(base, headers=AUTH) as resp:
                assert resp.status == 200
                full = await resp.read()
            with ipc.open_stream(io.BytesIO(full)) as r:
                t = r.read_all()
            assert t.num_rows == 10
            # range excluding everything -> 204
            async with http.get(
                base,
                params={"start": "2000-01-01T00:00:00Z", "end": "2000-01-02T00:00:00Z"},
                headers=AUTH,
            ) as resp:
                assert resp.status == 204
            # projection: host only (+ timestamp rides along), fewer bytes
            async with http.get(base, params={"fields": "host"}, headers=AUTH) as resp:
                assert resp.status == 200
                narrow = await resp.read()
            with ipc.open_stream(io.BytesIO(narrow)) as r:
                tn = r.read_all()
            assert set(tn.column_names) == {"host", "p_timestamp"}
            assert tn.num_rows == 10
            assert len(narrow) < len(full)
            # malformed bound -> 400, not a stack trace
            async with http.get(base, params={"start": "not-a-time"}, headers=AUTH) as resp:
                assert resp.status == 400
        await teardown(states, servers)

    run(scenario())


def test_fetch_staging_batches_passes_bounds_and_stats(tmp_path):
    from parseable_tpu.query.planner import TimeBounds

    async def scenario():
        states, servers = await boot_ingestors(tmp_path, n=1)
        q = make_parseable(tmp_path, "q", Mode.QUERY)

        def fetch():
            stats: dict = {}
            batches = C.fetch_staging_batches(
                q, "dist", time_bounds=TimeBounds(), columns={"host"}, stats=stats
            )
            return batches, stats

        batches, stats = await asyncio.get_running_loop().run_in_executor(None, fetch)
        assert sum(b.num_rows for b in batches) == 10
        assert set(batches[0].schema.names) == {"host", "p_timestamp"}
        assert stats["bytes"] > 0 and "errors" not in stats
        await teardown(states, servers)

    run(scenario())


def test_fanin_error_counted(tmp_path):
    from parseable_tpu.utils.metrics import REGISTRY

    p = make_parseable(tmp_path, "q", Mode.QUERY)
    domain = "http://127.0.0.1:1"

    def sample():
        return (
            REGISTRY.get_sample_value(
                "parseable_cluster_fanin_errors_total", {"peer": domain}
            )
            or 0.0
        )

    before = sample()
    stats: dict = {}
    out = C._fetch_one(p, domain, "nope", stats=stats)
    assert out == []
    assert sample() == before + 1
    assert stats["errors"] == 1


# ------------------------------------------------------ partial merge math


def test_combine_partials_matches_single_merge():
    """Distributed shape (blocks -> per-node combine -> cross-node merge)
    equals the single-node shape (all blocks -> one merge) exactly."""
    import numpy as np

    from parseable_tpu.query import partials as PT
    from parseable_tpu.query.executor import QueryExecutor
    from parseable_tpu.query.planner import plan as build_plan
    from parseable_tpu.query.sql import parse_sql

    rng = np.random.default_rng(5)
    blocks = []
    for _ in range(6):
        n = 500
        blocks.append(
            pa.table(
                {
                    "k": pa.array([f"g{int(i) % 7}" for i in rng.integers(0, 1 << 20, n)]),
                    "x": pa.array(rng.random(n) * 100),
                }
            )
        )
    lp = build_plan(
        parse_sql(
            "SELECT k, count(*) c, sum(x) s, avg(x) a, stddev(x) sd, "
            "min(x) mn, max(x) mx FROM t GROUP BY k"
        )
    )
    ex = QueryExecutor(lp)
    agg, rewritten, _ = ex.build_aggregator()
    group_exprs = lp.select.group_by
    parts = [PT.partial_from_block(b, group_exprs, agg.specs) for b in blocks]

    single = ex.finalize_from_interim(
        PT.merge_partials(list(parts), agg.specs, 1), rewritten
    )
    # distributed: nodes hold blocks [0:2], [2:5], [5:6]
    node_partials = [
        PT.combine_partials(parts[lo:hi], agg.specs, 1)
        for lo, hi in ((0, 2), (2, 5), (5, 6))
    ]
    dist = ex.finalize_from_interim(
        PT.merge_partials(node_partials, agg.specs, 1), rewritten
    )

    key = lambda r: r["k"]
    srows, drows = sorted(single.to_pylist(), key=key), sorted(dist.to_pylist(), key=key)
    assert len(srows) == len(drows) == 7
    for sr, dr in zip(srows, drows):
        assert sr["k"] == dr["k"] and sr["c"] == dr["c"]
        for col in ("s", "a", "sd", "mn", "mx"):
            assert sr[col] == pytest.approx(dr[col], rel=1e-9)


# -------------------------------------------------- satellites: cluster


def test_parse_prometheus_skips_nonfinite_and_malformed():
    text = "\n".join(
        [
            "# HELP x y",
            "good_total 5",
            'good_total{stream="a"} 7',
            "bad_nan NaN",
            "bad_inf +Inf",
            "bad_neginf -Inf",
            "malformed_line_without_value",
            "trailing_garbage 1 2 3",
            " 9",
        ]
    )
    totals = C.parse_prometheus(text)
    assert totals == {"good_total": 12.0}


def test_parse_prometheus_dated_label_escaping():
    text = "\n".join(
        [
            'billing{path="a,b",date="2024-01-02"} 3',
            'billing{date="2024-01-02",note="quo\\"te"} 4',
            'billing{date="2024-01-03"} 2',
            'billing{date="2024-01-03"} NaN',
            'other{stream="s"} 9',
        ]
    )
    dated = C.parse_prometheus_dated(text)
    assert dated == {
        ("billing", "2024-01-02"): 7.0,
        ("billing", "2024-01-03"): 2.0,
    }


def test_get_available_querier_probes_with_context(tmp_path, monkeypatch):
    """The liveness probe must carry `p` (TLS context + credentials) — it
    used to probe unconfigured."""
    p = make_parseable(tmp_path, "ing", Mode.INGEST)
    p.metastore.put_node(
        {"node_id": "q1", "node_type": "querier", "domain_name": "http://q1"}
    )
    seen = []

    def fake_liveness(domain, ctx=None):
        seen.append(ctx)
        return True

    monkeypatch.setattr(C, "check_liveness", fake_liveness)
    assert C.get_available_querier(p)["node_id"] == "q1"
    assert seen == [p]


def test_round_robin_skips_dead_then_resumes(tmp_path, monkeypatch):
    p = make_parseable(tmp_path, "ing", Mode.INGEST)
    for i in range(3):
        p.metastore.put_node(
            {"node_id": f"q{i}", "node_type": "querier", "domain_name": f"http://q{i}"}
        )
    order = [n["node_id"] for n in p.metastore.list_nodes("querier")]
    dead = {f"http://{order[1]}"}
    monkeypatch.setattr(
        C, "check_liveness", lambda domain, ctx=None: domain not in dead
    )
    picks = [C.get_available_querier(p)["node_id"] for _ in range(4)]
    live = [order[0], order[2]]
    assert picks == [live[0], live[1], live[0], live[1]]
    # the dead node recovers: rotation includes it again
    dead.clear()
    picks = [C.get_available_querier(p)["node_id"] for _ in range(3)]
    assert set(picks) == set(order)


def test_cluster_pool_lifecycle():
    pool = C.get_cluster_pool()
    assert pool is C.get_cluster_pool()
    assert pool.submit(lambda: 41 + 1).result() == 42
    C.shutdown_cluster_pool()
    fresh = C.get_cluster_pool()
    assert fresh is not pool
    assert fresh.submit(lambda: "ok").result() == "ok"
    C.shutdown_cluster_pool()
