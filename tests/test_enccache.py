"""Encoded-block disk cache (ops/enccache.py): the TPU-native hot tier's
device-feed layer (SURVEY §2 row 43). Roundtrip fidelity, variant
selection, invalidation-by-source-id, eviction, and the cold-query path
serving from cache with exact results."""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pytest

from parseable_tpu.ops.device import encode_table
from parseable_tpu.ops.enccache import EncodedBlockCache


@pytest.fixture()
def table() -> pa.Table:
    rng = np.random.default_rng(3)
    n = 5000
    return pa.table(
        {
            "host": pa.array([f"h{int(x)}" for x in rng.integers(0, 40, n)]),
            "status": pa.array(rng.choice([200.0, 404.0, 500.0], n)),
            "lat": pa.array(rng.random(n) * 9.0),
            "msg": pa.array(
                [f"m{int(x)}" if x % 5 else None for x in rng.integers(0, 30, n)]
            ),
        }
    )


def norm_cols(enc):
    out = {}
    for name, c in enc.columns.items():
        out[name] = (
            c.kind,
            c.values[: enc.num_rows].tolist(),
            c.valid[: enc.num_rows].tolist(),
            c.dictionary,
            c.vmin,
            c.vmax,
        )
    return out


def test_roundtrip_exact(tmp_path, table):
    cache = EncodedBlockCache(tmp_path)
    enc = encode_table(table, {"host", "status", "lat", "msg"})
    assert cache.put(b"src-1", enc)
    got = cache.get(b"src-1", {"host", "status", "lat", "msg"}, set())
    assert got is not None
    assert got.num_rows == enc.num_rows and got.block_rows == enc.block_rows
    assert norm_cols(got) == norm_cols(enc)


def test_narrow_dtypes_preserved(tmp_path, table):
    cache = EncodedBlockCache(tmp_path)
    enc = encode_table(table, {"host"})
    assert enc.columns["host"].values.dtype == np.int8  # 40-value dict
    cache.put(b"src-1", enc)
    got = cache.get(b"src-1", {"host"}, set())
    assert got.columns["host"].values.dtype == np.int8


def test_variant_merge_and_selection(tmp_path, table):
    """A numeric column stores both its f32 and forced-dict variants; each
    query shape picks the right one."""
    cache = EncodedBlockCache(tmp_path)
    enc_plain = encode_table(table, {"status"})
    cache.put(b"s", enc_plain)
    # group-by shape wants dict codes: miss until the variant is added
    assert cache.get(b"s", {"status"}, {"status"}) is None
    enc_forced = encode_table(table, {"status"}, dict_columns={"status"})
    cache.put(b"s", enc_forced)
    got_dict = cache.get(b"s", {"status"}, {"status"})
    assert got_dict is not None and got_dict.columns["status"].kind == "dict"
    got_num = cache.get(b"s", {"status"}, set())
    assert got_num is not None and got_num.columns["status"].kind == "num"
    # the forced numeric dict must never serve a non-group-by read
    assert got_num.columns["status"].values.dtype == np.float32


def test_string_dict_serves_both_shapes(tmp_path, table):
    cache = EncodedBlockCache(tmp_path)
    cache.put(b"s", encode_table(table, {"host"}))
    assert cache.get(b"s", {"host"}, {"host"}).columns["host"].kind == "dict"
    assert cache.get(b"s", {"host"}, set()).columns["host"].kind == "dict"


def test_missing_column_misses(tmp_path, table):
    cache = EncodedBlockCache(tmp_path)
    cache.put(b"s", encode_table(table, {"host"}))
    assert cache.get(b"s", {"host", "lat"}, set()) is None


def test_source_id_isolation(tmp_path, table):
    cache = EncodedBlockCache(tmp_path)
    cache.put(b"path|100|5000", encode_table(table, {"host"}))
    # same path, different size (rewritten object) -> different entry
    assert cache.get(b"path|200|5000", {"host"}, set()) is None


def test_timestamp_vmin_vmax_roundtrip(tmp_path):
    from datetime import datetime, timedelta

    from parseable_tpu import DEFAULT_TIMESTAMP_KEY

    base = datetime(2024, 5, 1)
    t = pa.table(
        {
            DEFAULT_TIMESTAMP_KEY: pa.array(
                [base + timedelta(seconds=i) for i in range(100)], pa.timestamp("ms")
            )
        }
    )
    cache = EncodedBlockCache(tmp_path)
    enc = encode_table(t, {DEFAULT_TIMESTAMP_KEY})
    cache.put(b"s", enc)
    got = cache.get(b"s", {DEFAULT_TIMESTAMP_KEY}, set())
    col = got.columns[DEFAULT_TIMESTAMP_KEY]
    assert (col.vmin, col.vmax) == (
        enc.columns[DEFAULT_TIMESTAMP_KEY].vmin,
        enc.columns[DEFAULT_TIMESTAMP_KEY].vmax,
    )


def test_eviction_by_budget(tmp_path, table):
    cache = EncodedBlockCache(tmp_path, budget_bytes=1)  # everything over
    enc = encode_table(table, {"host"})
    cache.put(b"a", enc)
    import time

    time.sleep(0.02)
    cache.put(b"b", enc)
    files = list(tmp_path.glob("*.enc"))
    assert len(files) <= 1  # oldest evicted


def test_cold_query_serves_from_cache(tmp_path):
    """Pipeline: ingest -> parquet+sidecar -> clear hot set -> cold query
    reads the sidecar (no parquet decode) with exact results."""
    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable
    from parseable_tpu.event.json_format import JsonEvent
    from parseable_tpu.ops import enccache as EC
    from parseable_tpu.ops.hotset import get_hotset
    from parseable_tpu.query.session import QuerySession

    opts = Options()
    opts.local_staging_path = tmp_path / "staging"
    opts.query_engine = "tpu"
    p = Parseable(opts, StorageOptions(backend="local-store", root=tmp_path / "data"))
    s = p.create_stream_if_not_exists("enc")
    rows = [{"host": f"h{i % 5}", "v": float(i)} for i in range(5000)]
    JsonEvent(rows, "enc").into_event(s.metadata).process(
        s, commit_schema=p.commit_schema
    )
    p.local_sync(shutdown=True)
    p.sync_all_streams()

    cache = EC.get_enccache(opts)
    assert cache is not None
    assert list((tmp_path / "staging" / "encoded_cache").glob("*.enc")), (
        "upload did not seed the encoded cache"
    )

    sess = QuerySession(p, engine="tpu")
    sql = "SELECT host, count(*) c, sum(v) s FROM enc GROUP BY host ORDER BY host"
    expected = QuerySession(p, engine="cpu").query(sql).to_json_rows()

    get_hotset().clear()
    hits_before = cache.hits

    # make a live parquet decode loud: cold hits must not need it
    import parseable_tpu.query.provider as PV

    reads = {"n": 0}
    orig = PV.StreamScan._read_parquet

    def counting(self, f):
        reads["n"] += 1
        return orig(self, f)

    PV.StreamScan._read_parquet = counting
    try:
        got = sess.query(sql).to_json_rows()
    finally:
        PV.StreamScan._read_parquet = orig
    assert got == expected
    assert cache.hits > hits_before, "cold query bypassed the encoded cache"
    assert reads["n"] == 0, "cold query still decoded parquet"
    p.shutdown()  # pools must not outlive the test (psan-thread-leak)


def test_concurrent_puts_no_corruption(tmp_path, table):
    """Racing writers must never install a torn file (unique tmp + lock)."""
    import threading

    cache = EncodedBlockCache(tmp_path)
    enc_plain = encode_table(table, {"status", "lat"})
    enc_forced = encode_table(table, {"status", "host"}, dict_columns={"status"})
    errs = []

    def writer(enc):
        for _ in range(10):
            if not cache.put(b"same-src", enc):
                pass

    ts = [threading.Thread(target=writer, args=(e,)) for e in (enc_plain, enc_forced)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    # file readable and serves both shapes
    assert cache.get(b"same-src", {"status"}, set()) is not None
    assert cache.get(b"same-src", {"status"}, {"status"}) is not None


def test_put_async_survives_strip(tmp_path, table):
    """put_async snapshots references BEFORE the hot set strips arrays."""
    import numpy as np
    import time

    from parseable_tpu.query.executor_tpu import _strip_host_values

    cache = EncodedBlockCache(tmp_path)
    enc = encode_table(table, {"host", "lat"})
    cache.put_async(b"async-src", enc)
    _strip_host_values(enc)  # what _encoded_block does right after
    for _ in range(100):
        if cache.get(b"async-src", {"host", "lat"}, set()) is not None:
            break
        time.sleep(0.05)
    got = cache.get(b"async-src", {"host", "lat"}, set())
    assert got is not None
    assert len(got.columns["lat"].values) >= got.num_rows
