"""Kafka consumer loop with a scripted fake broker (VERDICT r2 #5): the
poll / per-partition chunk / commit-after-flush / rebalance / graceful
shutdown loop executes fully; only the transport (confluent-kafka) is
swapped for the fake. Reference: src/connectors/kafka/{consumer.rs,
partition_stream.rs, sink.rs:93-122}."""

from __future__ import annotations

import threading

import pytest

from parseable_tpu.connectors.kafka import (
    ConnectorUnavailable,
    KafkaConfig,
    KafkaSource,
    Record,
)


class FakeConsumer:
    """Scripted consumer: a list of events — Record, ("revoke", parts),
    ("assign", parts), ("stop", source) — played back through poll()."""

    def __init__(self, script: list):
        self.script = list(script)
        self.commits: list[tuple[list, bool]] = []
        self.closed = False
        self._on_assign = None
        self._on_revoke = None

    def subscribe(self, topics, on_assign=None, on_revoke=None):
        self.topics = topics
        self._on_assign = on_assign
        self._on_revoke = on_revoke

    def poll(self, timeout):
        while self.script:
            ev = self.script.pop(0)
            if isinstance(ev, Record):
                return ev
            kind = ev[0]
            if kind == "revoke" and self._on_revoke:
                self._on_revoke(ev[1])
                continue
            if kind == "assign" and self._on_assign:
                self._on_assign(ev[1])
                continue
            if kind == "stop":
                ev[1].stop()
                return None
        return None

    def commit(self, offsets, sync=False):
        self.commits.append((list(offsets), sync))

    def close(self):
        self.closed = True


def committed_next(commits, topic, partition):
    """Latest committed next-offset for a partition."""
    out = None
    for offsets, _sync in commits:
        for t, p, off in offsets:
            if (t, p) == (topic, partition):
                out = off
    return out


@pytest.fixture()
def parseable(tmp_path):
    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable

    opts = Options()
    opts.local_staging_path = tmp_path / "staging"
    return Parseable(opts, StorageOptions(backend="local-store", root=tmp_path / "data"))


def staged_rows(p, stream: str) -> int:
    s = p.streams.get(stream)
    if s is None:
        return 0
    return sum(b.num_rows for b in s.staging_batches())


def make_source(parseable, script, **cfg_kw) -> tuple[KafkaSource, FakeConsumer]:
    cfg = KafkaConfig(
        bootstrap_servers="fake:9092", topics=["applogs"], buffer_size=3,
        buffer_timeout_secs=3600.0, **cfg_kw,
    )
    fake = FakeConsumer(script)
    return KafkaSource(parseable, cfg, consumer_factory=lambda: fake), fake


def recs(topic, partition, start, n):
    return [
        Record(topic, partition, start + i, b'{"n": %d, "p": %d}' % (start + i, partition))
        for i in range(n)
    ]


def test_chunk_flush_then_commit(parseable):
    """Offsets commit only AFTER the owning chunk flushes (at-least-once)."""
    source, fake = make_source(parseable, [])
    script = recs("applogs", 0, 0, 2)  # buffered, no flush (size 3)
    script.append(("stop", source))
    fake.script = script
    source.run()
    # shutdown drained the partial chunk, then committed
    assert staged_rows(parseable, "applogs") == 2
    assert committed_next(fake.commits, "applogs", 0) == 2
    assert fake.closed


def test_full_chunk_commits_inline(parseable):
    source, fake = make_source(parseable, [])
    script = recs("applogs", 0, 0, 3)  # exactly one full chunk
    script += recs("applogs", 0, 3, 1)  # one more buffered
    script.append(("stop", source))
    fake.script = script
    source.run()
    assert staged_rows(parseable, "applogs") == 4
    # first commit happened at the chunk boundary (next offset 3), before
    # the shutdown commit (next offset 4)
    nexts = [
        off for offsets, _ in fake.commits for t, p, off in offsets if (t, p) == ("applogs", 0)
    ]
    assert nexts == [3, 4]


def test_per_partition_chunks_and_commits(parseable):
    """Partitions chunk and commit independently (partition_stream.rs)."""
    source, fake = make_source(parseable, [])
    script = []
    # interleave two partitions; p0 fills a chunk (3), p1 stays partial (2)
    script += recs("applogs", 0, 10, 2)
    script += recs("applogs", 1, 70, 2)
    script += recs("applogs", 0, 12, 1)
    script.append(("stop", source))
    fake.script = script
    source.run()
    assert staged_rows(parseable, "applogs") == 5
    assert committed_next(fake.commits, "applogs", 0) == 13
    assert committed_next(fake.commits, "applogs", 1) == 72
    # p0's chunk commit fired before shutdown; p1 only at shutdown (sync)
    p0_commits = [
        (off, sync) for offsets, sync in fake.commits
        for t, p, off in offsets if (t, p) == ("applogs", 0)
    ]
    assert p0_commits[0] == (13, False)


def test_rebalance_revoke_flushes_and_sync_commits(parseable):
    """Revoked partitions flush + commit synchronously before handoff."""
    source, fake = make_source(parseable, [])
    script = recs("applogs", 0, 0, 2)  # buffered
    script.append(("revoke", [("applogs", 0)]))
    script.append(("stop", source))
    fake.script = script
    source.run()
    assert source.rebalances == 1
    assert staged_rows(parseable, "applogs") == 2
    # the revoke commit is synchronous and covers the buffered offsets
    revoke_commits = [
        (off, sync) for offsets, sync in fake.commits
        for t, p, off in offsets if (t, p) == ("applogs", 0)
    ]
    assert (2, True) in revoke_commits


def test_at_least_once_across_simulated_rebalance(parseable):
    """e2e topic -> stream -> query with a rebalance mid-stream: every
    record lands exactly once here (the fake redelivers nothing), and the
    commit watermarks prove redelivery could only duplicate, never lose."""
    source, fake = make_source(parseable, [])
    script = recs("applogs", 0, 0, 3)  # full chunk -> flush+commit
    script += recs("applogs", 1, 0, 2)  # buffered on p1
    script.append(("revoke", [("applogs", 1)]))  # p1 moves away
    script.append(("assign", [("applogs", 0)]))
    script += recs("applogs", 0, 3, 3)  # another full chunk
    script.append(("stop", source))
    fake.script = script
    source.run()
    assert staged_rows(parseable, "applogs") == 8
    # every commit watermark trails or equals the rows durably staged
    assert committed_next(fake.commits, "applogs", 0) == 6
    assert committed_next(fake.commits, "applogs", 1) == 2

    from parseable_tpu.query.session import QuerySession

    rows = (
        QuerySession(parseable, engine="cpu")
        .query("SELECT count(*) c FROM applogs")
        .to_json_rows()
    )
    assert rows == [{"c": 8}]


def test_age_based_drain_commits(parseable):
    source, fake = make_source(parseable, [])
    source.config.buffer_timeout_secs = 0.0  # everything is instantly due
    script = recs("applogs", 0, 0, 1)
    # a poll tick after the record lets tick() drain it
    script.append(("stop", source))
    fake.script = script
    source.run()
    assert staged_rows(parseable, "applogs") == 1
    assert committed_next(fake.commits, "applogs", 0) == 1


def test_broker_error_records_skipped(parseable):
    source, fake = make_source(parseable, [])
    script = [Record("applogs", 0, -1, b"", error="broker gone")]
    script += recs("applogs", 0, 5, 3)
    script.append(("stop", source))
    fake.script = script
    source.run()
    assert staged_rows(parseable, "applogs") == 3
    assert committed_next(fake.commits, "applogs", 0) == 8


def test_kafka_flush_rides_columnar_lane(parseable):
    """The sink flush routes through the three-tier native ladder: a
    uniform chunk must land via the columnar lane (proved by the
    parseable_ingest_native_total counter), not the per-record Python
    wrap — that path is reserved for malformed batches."""
    from parseable_tpu import native
    from parseable_tpu.utils.metrics import REGISTRY

    if not native.native_available():
        pytest.skip("native fastpath unavailable")

    def lane(ln, r):
        return (
            REGISTRY.get_sample_value(
                "parseable_ingest_native_total", {"lane": ln, "result": r}
            )
            or 0.0
        )

    before = lane("columnar", "hit")
    source, fake = make_source(parseable, [])
    script = recs("applogs", 0, 0, 3)  # exactly one full chunk -> flush
    script.append(("stop", source))
    fake.script = script
    source.run()
    assert staged_rows(parseable, "applogs") == 3
    assert lane("columnar", "hit") > before, "kafka flush missed the columnar lane"


def test_malformed_payloads_survive(parseable):
    source, fake = make_source(parseable, [])
    script = [
        Record("applogs", 0, 0, b"not-json{{"),
        Record("applogs", 0, 1, b'[1, 2]'),
        Record("applogs", 0, 2, b'{"ok": 1}'),
    ]
    script.append(("stop", source))
    fake.script = script
    source.run()
    assert staged_rows(parseable, "applogs") == 3


def test_consumer_unavailable_without_binding(parseable):
    cfg = KafkaConfig(bootstrap_servers="b", topics=["t"])
    with pytest.raises(ConnectorUnavailable):
        KafkaSource(parseable, cfg)  # no injected factory, no confluent-kafka


def test_graceful_stop_from_another_thread(parseable):
    """stop() from outside the loop drains and closes."""
    source_holder: dict = {}

    class BlockingFake(FakeConsumer):
        def poll(self, timeout):
            rec = super().poll(timeout)
            if rec is None and not self.script:
                # simulate an idle broker until stop() lands
                source_holder["source"].stop()
            return rec

    cfg = KafkaConfig(bootstrap_servers="b", topics=["applogs"], buffer_size=100)
    fake = BlockingFake(recs("applogs", 0, 0, 2))
    source = KafkaSource(parseable, cfg, consumer_factory=lambda: fake)
    source_holder["source"] = source
    t = threading.Thread(target=source.run)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    assert fake.closed
    assert staged_rows(parseable, "applogs") == 2
