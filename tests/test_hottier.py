"""Disk hot tier: budgets, reconcile, eviction, scan integration."""

import pytest

from parseable_tpu.event.json_format import JsonEvent
from parseable_tpu.query.session import QuerySession
from parseable_tpu.storage.hottier import HotTierManager, parse_human_size


def load_stream(p, name, n=500):
    stream = p.create_stream_if_not_exists(name)
    recs = [{"k": f"v{i % 7}", "x": float(i)} for i in range(n)]
    ev = JsonEvent(recs, name).into_event(stream.metadata)
    ev.process(stream, commit_schema=p.commit_schema)
    p.local_sync(shutdown=True)
    p.sync_all_streams()
    return stream


def test_parse_human_size():
    assert parse_human_size("10GiB") == 10 * 2**30
    assert parse_human_size("500 MB") == 500 * 10**6
    with pytest.raises(ValueError):
        parse_human_size("lots")


def test_reconcile_downloads_and_scan_uses_local(parseable, tmp_path):
    p = parseable
    load_stream(p, "tiered")
    mgr = HotTierManager(p, tmp_path / "ht")
    p.hot_tier = mgr
    mgr.set_budget("tiered", 100 * 2**20)
    n = mgr.reconcile("tiered")
    assert n >= 1
    assert mgr.used_bytes("tiered") > 0
    # scan reads the hot-tier copy: bytes_scanned counts local reads too but
    # the object store GET path is skipped (no NoSuchKey surprises either)
    sess = QuerySession(p, engine="cpu")
    res = sess.query("SELECT count(*) c FROM tiered")
    assert res.to_json_rows()[0]["c"] == 500
    # second reconcile is a no-op
    assert mgr.reconcile("tiered") == 0


def test_budget_eviction(parseable, tmp_path):
    p = parseable
    load_stream(p, "small", n=2000)
    mgr = HotTierManager(p, tmp_path / "ht")
    mgr.budgets["small"] = 1  # sub-minimum budget forced directly
    mgr.reconcile("small")
    assert mgr.used_bytes("small") <= 1 or mgr.used_bytes("small") == 0


def test_disable_clears(parseable, tmp_path):
    p = parseable
    load_stream(p, "gone")
    mgr = HotTierManager(p, tmp_path / "ht")
    mgr.set_budget("gone", 100 * 2**20)
    mgr.reconcile("gone")
    assert mgr.used_bytes("gone") > 0
    mgr.disable("gone")
    assert mgr.used_bytes("gone") == 0
    assert mgr.get_budget("gone") is None


def test_disk_usage_guard_evicts_oldest(tmp_path, monkeypatch):
    """Above the disk ceiling the guard evicts oldest hot-tier files across
    streams until under (reference: hottier.rs:1596-1665)."""
    import shutil as _shutil
    from collections import namedtuple

    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable
    from parseable_tpu.storage.hottier import HotTierManager

    opts = Options()
    opts.local_staging_path = tmp_path / "staging"
    p = Parseable(opts, StorageOptions(backend="local-store", root=tmp_path / "data"))
    mgr = HotTierManager(p, tmp_path / "hottier")
    # oldest data lives in stream "z" — eviction order must follow the
    # date, not the stream name
    for stream, day in (("z", "2024-05-01"), ("a", "2024-05-02"), ("a", "2024-05-03")):
        f = mgr.base / stream / f"date={day}" / "x.data.parquet"
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_bytes(b"x" * 128)

    Usage = namedtuple("Usage", "total used free")
    calls = {"n": 0}

    def fake_usage(path):
        # over the ceiling for the first three checks (initial + 2 evictions)
        calls["n"] += 1
        over = calls["n"] <= 3
        return Usage(total=100, used=95 if over else 10, free=5)

    import parseable_tpu.storage.hottier as H

    monkeypatch.setattr(H.shutil, "disk_usage", fake_usage)
    evicted = mgr.disk_usage_guard()
    assert evicted == 2
    remaining = sorted(str(f.relative_to(mgr.base)) for f in mgr.base.rglob("*.parquet"))
    # the two oldest dates went first, across streams
    assert remaining == ["a/date=2024-05-03/x.data.parquet"]


def test_internal_streams_auto_hot_tiered(parseable, tmp_path):
    """pstats/pmeta auto-hot-tier (reference: hottier.rs:1667-1743): the
    dataset-stats stream gets a budget without operator action the moment
    it exists, and field-stats queries are served from the local tier even
    when the object-store copy is gone."""
    p = parseable
    p.options.collect_dataset_stats = True
    stream = p.create_stream_if_not_exists("statsy")
    ev = JsonEvent(
        [{"k": "x", "n": 1.0}, {"k": "y", "n": 2.0}], "statsy"
    ).into_event(stream.metadata)
    ev.process(stream, commit_schema=p.commit_schema)
    p.local_sync(shutdown=True)
    p.sync_all_streams()
    # pstats rows land via the upload hook; sync them to storage too
    p.local_sync(shutdown=True)
    p.sync_all_streams()

    mgr = HotTierManager(p, tmp_path / "ht")
    p.hot_tier = mgr
    assert mgr.get_budget("pstats") is None
    mgr.tick()
    assert mgr.get_budget("pmeta") == mgr.INTERNAL_PMETA_BYTES
    assert mgr.get_budget("pstats") == mgr.INTERNAL_PSTATS_BYTES
    assert mgr.used_bytes("pstats") > 0, "pstats parquet not tiered"

    # the strong proof queries hit the tier: remove the object-store
    # copies — the field-stats query must still answer from local disk
    from pathlib import Path

    data_root = Path(p.provider.get_endpoint())
    deleted = 0
    for f in (data_root / "pstats").rglob("*.parquet"):
        f.unlink()
        deleted += 1
    assert deleted, "expected pstats parquet in the object store"
    res = QuerySession(p, engine="cpu").query(
        "SELECT count(*) c FROM pstats WHERE stream = 'statsy'", "1h", "now"
    )
    assert res.to_json_rows()[0]["c"] >= 2
