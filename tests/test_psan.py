"""psan (runtime concurrency sanitizer) tests.

Seeded-bug fixture suite: each detector catches its class of bug (true
positive), idiomatic code passes (true negative), and `# plint: disable=`
suppression is honored — the same contract plint's rule tests enforce for
the static checker. Plus regression tests for the real defects psan
surfaced and this PR fixed:

- the per-flush fire-and-forget `otlp-export` thread in utils/telemetry.py
  (now tracked, at most one in flight, joined by Tracer.drain());
- the module-global `device-warmer` thread in ops/link.py with no stop
  path (now drained by shutdown_warmer());
- scrypt password verification on the event loop in the auth middleware
  (psan-loop-block: rbac/__init__.py hash_password blocked the loop 58ms;
  cache misses — including every wrong-password attempt — now verify on
  the worker pool);
- the hotset/prefetch claim() interleaving where a ship completing between
  `peek()` and `get(touch=...)` promoted prefetch cargo into the protected
  segment (consumption now fetches untouched and lets `consumed()` decide
  atomically, with `DeviceHotSet.touch()` applying proven reuse after).

The fixture tests run against a *scoped* sanitizer session: when the
whole suite already runs under P_PSAN=1 the global runtime is reused
(fixture findings live outside the repo root, which the gate ignores);
otherwise the session enables/disables the patches around each scenario.
"""

from __future__ import annotations

import importlib
import sys
import textwrap
import threading
import time
from contextlib import contextmanager
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]


@contextmanager
def psan_session(tmp_path, modname: str, source: str):
    """Scoped sanitizer over one fixture module written to `tmp_path`.

    Yields (module, runtime, new_findings) where new_findings() returns the
    findings this scenario produced inside the fixture module."""
    from parseable_tpu.analysis.psan import contracts, runtime

    rt = runtime.get_runtime()
    was_enabled = rt.enabled
    path = tmp_path / f"{modname}.py"
    path.write_text(textwrap.dedent(source), encoding="utf-8")
    sys.path.insert(0, str(tmp_path))
    saved_prefixes = rt.watch_prefixes
    pre = {f.fingerprint for f in rt.findings()}
    try:
        if was_enabled:
            rt.watch_prefixes = rt.watch_prefixes + (modname,)
            cs = contracts.build_contracts(tmp_path, [f"{modname}.py"])
        else:
            rt.enable(root=str(tmp_path), extra_prefixes=(modname,))
            cs = contracts.build_contracts(tmp_path, [f"{modname}.py"])
        contracts.instrument(rt, cs)
        mod = importlib.import_module(modname)

        def new_findings():
            return [
                f
                for f in rt.findings()
                if f.fingerprint not in pre and modname in f.path
            ]

        yield mod, rt, new_findings
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop(modname, None)
        rt.watch_prefixes = saved_prefixes
        if not was_enabled:
            rt.disable()
            rt.reset_findings()


# ------------------------------------------------------------ psan-race


RACE_SRC = """
    import threading

    class {cls}:
        def __init__(self):
            self._lock = threading.Lock()
            self.value = 0  # guarded-by: self._lock

        def safe_add(self):
            with self._lock:
                self.value += 1

        def racy_add(self):
            self.value += 1{suffix}
"""


def test_race_detector_catches_unguarded_write(tmp_path):
    src = RACE_SRC.format(cls="RacyCounter", suffix="")
    with psan_session(tmp_path, "psan_fix_race_tp", src) as (mod, rt, new):
        c = mod.RacyCounter()
        c.safe_add()  # main thread takes shared ownership first
        t = threading.Thread(target=c.racy_add, name="racer")
        t.start()
        t.join()
        races = [f for f in new() if f.rule == "psan-race"]
        assert races, "unguarded cross-thread write not detected"
        assert "RacyCounter.value" in races[0].message
        assert "self._lock" in races[0].message  # cites the declared guard
        assert "racy_add" in races[0].message  # both stacks in the report
        assert "safe_add" in races[0].message or "previously" in races[0].message


def test_race_detector_clean_on_locked_access(tmp_path):
    src = RACE_SRC.format(cls="CleanCounter", suffix="")
    with psan_session(tmp_path, "psan_fix_race_tn", src) as (mod, rt, new):
        c = mod.CleanCounter()
        threads = [
            threading.Thread(target=lambda: [c.safe_add() for _ in range(50)])
            for _ in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # owner reads after join are exempt too (join happens-before)
        with c._lock:
            total = c.value
        assert total == 150
        assert [f for f in new() if f.rule == "psan-race"] == []


def test_race_detector_honors_suppression(tmp_path):
    src = RACE_SRC.format(
        cls="SuppressedCounter", suffix="  # plint: disable=psan-race"
    )
    with psan_session(tmp_path, "psan_fix_race_sup", src) as (mod, rt, new):
        before = rt.stats()["suppressed"]
        c = mod.SuppressedCounter()
        c.safe_add()
        t = threading.Thread(target=c.racy_add)
        t.start()
        t.join()
        assert [f for f in new() if f.rule == "psan-race"] == []
        assert rt.stats()["suppressed"] > before


def test_race_detector_init_then_single_reader_clean(tmp_path):
    """Publication to ONE other thread with read-only sharing is not a
    race (Eraser initialization + read-share states)."""
    src = RACE_SRC.format(cls="PublishOnly", suffix="")
    with psan_session(tmp_path, "psan_fix_race_pub", src) as (mod, rt, new):
        c = mod.PublishOnly()
        seen = []
        t = threading.Thread(target=lambda: seen.append(c.value))  # bare read
        t.start()
        t.join()
        assert seen == [0]
        assert [f for f in new() if f.rule == "psan-race"] == []


# ------------------------------------------------------- psan-lock-order


ORDER_SRC = """
    import threading

    # lock-order: OrdFix.a < OrdFix.b

    class OrdFix:
        def __init__(self):
            self.a = threading.Lock()
            self.b = threading.Lock()

        def forward(self):
            with self.a:
                with self.b:
                    pass

        def inverted(self):
            with self.b:
                with self.a:
                    pass
"""


def test_lock_order_contradiction_without_deadlock(tmp_path):
    """The declared-hierarchy contradiction fires from ONE thread's
    acquisition order — no actual deadlock needed."""
    with psan_session(tmp_path, "psan_fix_order", ORDER_SRC) as (mod, rt, new):
        o = mod.OrdFix()
        o.inverted()  # b then a: contradicts `# lock-order: OrdFix.a < OrdFix.b`
        finds = [f for f in new() if f.rule == "psan-lock-order"]
        assert finds, "declared-order contradiction not detected"
        assert "OrdFix.a" in finds[0].message and "OrdFix.b" in finds[0].message
        assert "lock-order" in finds[0].message


CYCLE_SRC = """
    import threading

    class CycFix:
        def __init__(self):
            self.x = threading.Lock()
            self.y = threading.Lock()

        def xy(self):
            with self.x:
                with self.y:
                    pass

        def yx(self):
            with self.y:
                with self.x:
                    pass
"""


def test_lock_order_cycle_detected(tmp_path):
    with psan_session(tmp_path, "psan_fix_cycle", CYCLE_SRC) as (mod, rt, new):
        c = mod.CycFix()
        c.xy()
        c.yx()
        finds = [f for f in new() if f.rule == "psan-lock-order"]
        assert finds and "cycle" in finds[0].message


def test_lock_order_consistent_nesting_clean(tmp_path):
    with psan_session(tmp_path, "psan_fix_nest_ok", CYCLE_SRC) as (mod, rt, new):
        c = mod.CycFix()
        for _ in range(3):
            c.xy()  # always x < y: consistent
        assert [f for f in new() if f.rule == "psan-lock-order"] == []


# ------------------------------------------------------------ psan-stall


STALL_SRC = """
    import threading

    class StallFix:
        def __init__(self):
            self.lock = threading.Lock()

        def grab(self):
            return self.lock
"""


def test_watchdog_dumps_on_blocked_acquisition(tmp_path):
    with psan_session(tmp_path, "psan_fix_stall", STALL_SRC) as (mod, rt, new):
        old_wd = rt.watchdog_s
        rt.watchdog_s = 0.2
        try:
            s = mod.StallFix()
            holder_has_it = threading.Event()
            release = threading.Event()

            def holder():
                with s.grab():
                    holder_has_it.set()
                    release.wait(10)

            t = threading.Thread(target=holder)
            t.start()
            assert holder_has_it.wait(5)
            got = s.grab().acquire(timeout=1.0)  # blocks past the watchdog
            if got:
                s.grab().release()
            release.set()
            t.join()
            finds = [f for f in rt.findings() if f.rule == "psan-stall"]
            assert finds, "blocked acquisition did not trip the watchdog"
            assert "blocked" in finds[0].message
            # the stall site is THIS test file (deliberate sabotage): keep
            # the session gate about the tree, not the detector's own test
            rt.remove_findings(f.fingerprint for f in finds)
        finally:
            rt.watchdog_s = old_wd


# ------------------------------------------------------- psan-loop-block


LOOP_SRC = """
    import asyncio
    import time

    async def slow_handler():
        time.sleep(0.12)  # blocks the loop: the exact anti-pattern

    async def good_handler():
        await asyncio.sleep(0.12)

    def run_slow():
        asyncio.new_event_loop().run_until_complete(slow_handler())

    def run_good():
        asyncio.new_event_loop().run_until_complete(good_handler())
"""


def test_loop_monitor_attributes_blocking_sleep(tmp_path):
    with psan_session(tmp_path, "psan_fix_loop", LOOP_SRC) as (mod, rt, new):
        mod.run_slow()
        deadline = time.monotonic() + 2
        finds = []
        while time.monotonic() < deadline and not finds:
            finds = [f for f in new() if f.rule == "psan-loop-block"]
            time.sleep(0.02)
        assert finds, "loop-blocking time.sleep not detected"
        f = finds[0]
        assert "slow_handler" in f.message
        # attributed to the offending frame, not the asyncio machinery
        assert "psan_fix_loop" in f.path
        assert "time.sleep(0.12)" in f.snippet


def test_loop_monitor_clean_on_awaited_sleep(tmp_path):
    with psan_session(tmp_path, "psan_fix_loop_ok", LOOP_SRC) as (mod, rt, new):
        mod.run_good()
        time.sleep(0.1)
        assert [f for f in new() if f.rule == "psan-loop-block"] == []


# ------------------------------------------------------ psan-thread-leak


LEAK_SRC = """
    import threading

    STOP = threading.Event()

    def leak_worker():
        t = threading.Thread(target=STOP.wait, name="fixture-leaker", daemon=True)
        t.start()
        return t

    def tidy_worker():
        t = threading.Thread(target=lambda: None, name="fixture-tidy")
        t.start()
        t.join()
        return t

    def allowlisted_worker():
        t = threading.Thread(target=STOP.wait, name="device-warmer", daemon=True)
        t.start()
        return t
"""


def test_leak_detector_flags_surviving_thread(tmp_path):
    with psan_session(tmp_path, "psan_fix_leak", LEAK_SRC) as (mod, rt, new):
        old_grace = rt.leak_grace_ms
        rt.leak_grace_ms = 50.0
        try:
            pre_t, pre_e = rt.thread_snapshot(), rt.executor_snapshot()
            mod.leak_worker()
            rt.check_leaks(pre_t, pre_e)
            finds = [f for f in new() if f.rule == "psan-thread-leak"]
            assert finds, "surviving worker not detected"
            assert "fixture-leaker" in finds[0].message
        finally:
            mod.STOP.set()
            rt.leak_grace_ms = old_grace


def test_leak_detector_clean_on_joined_and_allowlisted(tmp_path):
    with psan_session(tmp_path, "psan_fix_leak_ok", LEAK_SRC) as (mod, rt, new):
        old_grace = rt.leak_grace_ms
        rt.leak_grace_ms = 50.0
        try:
            pre_t, pre_e = rt.thread_snapshot(), rt.executor_snapshot()
            mod.tidy_worker()  # joined before the check
            mod.allowlisted_worker()  # known daemon name
            rt.check_leaks(pre_t, pre_e)
            assert [f for f in new() if f.rule == "psan-thread-leak"] == []
        finally:
            mod.STOP.set()


EXEC_LEAK_SRC = """
    from concurrent.futures import ThreadPoolExecutor

    def make_pool():
        pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="fixture-pool")
        pool.submit(lambda: None)
        return pool
"""


def test_leak_detector_flags_unshut_executor(tmp_path):
    with psan_session(tmp_path, "psan_fix_pool", EXEC_LEAK_SRC) as (mod, rt, new):
        old_grace = rt.leak_grace_ms
        rt.leak_grace_ms = 50.0
        pool = None
        try:
            pre_t, pre_e = rt.thread_snapshot(), rt.executor_snapshot()
            pool = mod.make_pool()
            rt.check_leaks(pre_t, pre_e)
            finds = [f for f in new() if f.rule == "psan-thread-leak"]
            assert finds and "fixture-pool" in finds[0].message
            # shut down -> clean on a fresh snapshot window
            pre_t, pre_e = rt.thread_snapshot(), rt.executor_snapshot()
            pool.shutdown(wait=True)
            rt.check_leaks(pre_t, pre_e)
            assert len([f for f in new() if f.rule == "psan-thread-leak"]) == len(finds)
        finally:
            if pool is not None:
                pool.shutdown(wait=False)
            rt.leak_grace_ms = old_grace


# ----------------------------------------- regressions: what psan found


def test_tracer_export_thread_tracked_and_drained(monkeypatch):
    """Regression (psan-thread-leak seed: utils/telemetry.py otlp-export):
    the per-flush exporter used to be a fire-and-forget daemon, one per
    tipped batch. Now: at most ONE in flight, and drain() joins it."""
    from parseable_tpu.utils import telemetry as T

    tr = T.Tracer(endpoint="http://127.0.0.1:9")
    gate = threading.Event()
    flushed = threading.Event()

    def slow_flush():
        gate.wait(5)
        flushed.set()
        return True

    monkeypatch.setattr(tr, "_flush_locked", slow_flush)
    tr._spawn_export()
    first = [t for t in threading.enumerate() if t.name == "otlp-export"]
    assert len(first) == 1
    tr._spawn_export()  # in flight: must NOT stack a second exporter
    assert len([t for t in threading.enumerate() if t.name == "otlp-export"]) == 1
    gate.set()
    tr.drain(timeout=5)
    assert flushed.is_set()
    assert all(t.name != "otlp-export" for t in threading.enumerate()), (
        "drain() left an exporter thread alive"
    )


def test_psan_leak_detector_catches_undrained_export(monkeypatch):
    """The satellite contract: if the exporter regresses to an unjoined
    thread surviving a test, psan's leak accounting reports it."""
    from parseable_tpu.analysis.psan import runtime as R
    from parseable_tpu.utils import telemetry as T

    rt = R.get_runtime()
    was_enabled = rt.enabled
    if not was_enabled:
        rt.enable(root=str(REPO_ROOT))
    pre = {f.fingerprint for f in rt.findings()}
    old_grace = rt.leak_grace_ms
    rt.leak_grace_ms = 50.0
    gate = threading.Event()
    try:
        tr = T.Tracer(endpoint="http://127.0.0.1:9")
        monkeypatch.setattr(tr, "_flush_locked", lambda: gate.wait(10))
        pre_t, pre_e = rt.thread_snapshot(), rt.executor_snapshot()
        tr._spawn_export()  # simulate "still in flight at teardown"
        rt.check_leaks(pre_t, pre_e)
        finds = [
            f
            for f in rt.findings()
            if f.fingerprint not in pre
            and f.rule == "psan-thread-leak"
            and "otlp-export" in f.message
        ]
        assert finds, "undrained otlp-export thread not caught"
        gate.set()
        tr.drain(timeout=5)
    finally:
        gate.set()
        rt.leak_grace_ms = old_grace
        if not was_enabled:
            rt.disable()
            rt.reset_findings()
        else:
            # this test SABOTAGED product code on purpose; the session gate
            # must judge the tree, not the sabotage
            rt.remove_findings(
                f.fingerprint for f in rt.findings() if f.fingerprint not in pre
            )


def test_warmer_shutdown_joins_and_restarts():
    """Regression (pool-lifecycle: ops/link.py device-warmer had no stop
    path): shutdown_warmer() drains + joins; warming works again after."""
    from parseable_tpu.ops import link as L

    ran = threading.Event()
    assert L.warm_async(("psan-k1",), ran.set)
    assert ran.wait(5)
    L.shutdown_warmer()
    assert all(t.name != "device-warmer" for t in threading.enumerate()), (
        "shutdown_warmer left the warmer running"
    )
    ran2 = threading.Event()
    assert L.warm_async(("psan-k2",), ran2.set)  # fresh warmer spins up
    assert ran2.wait(5)
    L.shutdown_warmer()


def test_prefetch_consumption_never_promotes():
    """Regression (psan seed: hotset/prefetch claim() interleaving): the
    consumer now fetches with touch=False unconditionally and applies
    `DeviceHotSet.touch()` only when `consumed()` says the hit was NOT the
    prefetcher's planned consumption — there is no longer a peek-then-get
    window in which a completing ship gets promoted as proven reuse."""
    from parseable_tpu.ops.hotset import DeviceHotSet, HotEntry
    from parseable_tpu.ops.prefetch import ScanPrefetcher

    hs = DeviceHotSet(budget_bytes=10_000, policy="cost", ship_cost=lambda n: 1.0)
    key = ("blk", "cols")
    shipped = threading.Event()

    def ship(sid):
        hs.put(key, HotEntry(dev={}, meta=None, nbytes=100))
        shipped.set()
        return key

    pf = ScanPrefetcher([b"a", b"b"], ship=ship, depth=1)
    try:
        pf.on_block(b"a")  # schedules b"b"; the worker ships it
        assert shipped.wait(5)
        # consumer path: untouched fetch, then consumed() decides
        entry = hs.get(key, touch=False)
        assert entry is not None
        assert pf.claim(b"b") or True  # ship already landed; claim is moot
        was_prefetch = pf.consumed(key)
        assert was_prefetch
        slot = hs._entries[key]
        assert slot.freq == 1 and slot.probation, (
            "planned prefetch consumption was promoted as proven reuse"
        )
        # a REAL re-touch afterwards is proven reuse and promotes
        hs.touch(key)
        slot = hs._entries[key]
        assert slot.freq == 2 and not slot.probation
        assert pf.hits == 1
    finally:
        pf.close()


def test_hotset_touch_matches_get_touch_semantics():
    from parseable_tpu.ops.hotset import DeviceHotSet, HotEntry

    a = DeviceHotSet(budget_bytes=10_000, policy="cost", ship_cost=lambda n: 1.0)
    b = DeviceHotSet(budget_bytes=10_000, policy="cost", ship_cost=lambda n: 1.0)
    for hs in (a, b):
        hs.put(("k",), HotEntry(dev={}, meta=None, nbytes=64))
    a.get(("k",), touch=True)
    b.get(("k",), touch=False)
    b.touch(("k",))
    sa, sb = a._entries[("k",)], b._entries[("k",)]
    assert (sa.freq, sa.probation) == (sb.freq, sb.probation)
    assert a._protected_bytes == b._protected_bytes


def test_auth_scrypt_leaves_the_event_loop(tmp_path):
    """Regression (psan-loop-block: rbac hash_password blocked the loop
    58ms): a Basic-auth credential-cache MISS must verify scrypt on a
    worker thread, never on the event loop; cache hits stay inline."""
    import asyncio

    from tests.test_server import AUTH, make_state, run, with_client

    state = make_state(tmp_path)
    verify_threads: list[int] = []
    orig = state.rbac.authenticate

    def recording_authenticate(user, pw):
        verify_threads.append(threading.get_ident())
        return orig(user, pw)

    state.rbac.authenticate = recording_authenticate

    async def fn(client):
        loop_thread = threading.get_ident()
        r = await client.get("/api/v1/liveness")  # unauthenticated: no verify
        assert r.status == 200
        r = await client.get("/api/v1/logstream", headers=AUTH)
        assert r.status == 200
        assert verify_threads, "slow-path authenticate never ran"
        assert loop_thread not in verify_threads, (
            "scrypt verification ran on the event loop"
        )
        # second request: cache hit, no slow-path call at all
        n = len(verify_threads)
        r = await client.get("/api/v1/logstream", headers=AUTH)
        assert r.status == 200
        assert len(verify_threads) == n

    run(with_client(state, fn))


def test_rbac_cached_authenticate_fast_path():
    from parseable_tpu.rbac import RbacStore

    rbac = RbacStore()
    rbac.put_user("admin", "admin")
    user, decided = rbac.try_cached_authenticate("admin", "admin")
    assert not decided and user is None  # cold cache: needs scrypt
    assert rbac.authenticate("admin", "admin") is not None
    user, decided = rbac.try_cached_authenticate("admin", "admin")
    assert decided and user is not None  # warm: decided inline
    user, decided = rbac.try_cached_authenticate("admin", "wrong")
    assert decided and user is None  # warm wrong password: decided inline
    user, decided = rbac.try_cached_authenticate("ghost", "x")
    assert decided and user is None  # unknown user: decided inline


# ----------------------------------------------------- report machinery


def test_findings_share_plint_fingerprints_and_baseline(tmp_path):
    from parseable_tpu.analysis.framework import Finding
    from parseable_tpu.analysis.psan.report import assemble_report, render_lines

    f = Finding(
        rule="psan-race",
        path="parseable_tpu/x.py",
        line=10,
        message="m",
        snippet="self.v += 1",
    )
    rep = assemble_report([f], {"raw_hits": {"psan-race": 1}}, tmp_path)
    assert not rep["clean"] and len(rep["findings"]) == 1
    # baseline the fingerprint -> clean (same schema as plint's baseline)
    (tmp_path / ".psan-baseline.json").write_text(
        '{"findings": [{"fingerprint": "%s"}]}' % f.fingerprint
    )
    rep2 = assemble_report([f], {}, tmp_path)
    assert rep2["clean"] and len(rep2["baselined"]) == 1
    assert any("psan:" in line for line in render_lines(rep2))


def test_contracts_shared_with_plint(tmp_path):
    """One annotation source: the guarded-by/lock-order comments psan
    parses are the same ones plint's rules read."""
    from parseable_tpu.analysis.psan.contracts import build_contracts

    cs = build_contracts(REPO_ROOT, ["parseable_tpu"])
    guarded = {k[1]: set(v) for k, v in cs.guarded.items()}
    # spot-check known contracts from the live tree
    assert "_rows" in guarded.get("SpanSink", set())
    assert "_entries" in guarded.get("DeviceHotSet", set())
    assert ("Tracer._flush_inflight", "Tracer._lock") in cs.declared_order
    assert ("Streams._lock", "Stream.lock") in cs.declared_order


def test_repo_baseline_is_empty():
    """Policy gate: like plint's, the psan baseline stays EMPTY."""
    import json

    doc = json.loads((REPO_ROOT / ".psan-baseline.json").read_text())
    assert doc["findings"] == []
