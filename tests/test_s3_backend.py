"""S3 backend against the in-process S3 mock (MinIO stand-in, SURVEY §4).

Covers the SigV4 client's full trait surface — CRUD, listing with
pagination and delimiter, multipart upload, parallel ranged download,
batch prefix delete — then drives the complete ingest → staging → upload →
catalog → query pipeline with S3 as the object store (VERDICT Next#4:
"the existing storage/upload test suite runs green against [S3] in
addition to LocalFS").
"""

import pytest

from parseable_tpu.storage.object_storage import NoSuchKey
from parseable_tpu.storage.s3 import S3Storage

from tests.s3_mock import serve


@pytest.fixture()
def s3():
    srv, endpoint, state = serve()
    storage = S3Storage(
        "testbucket",
        region="us-east-1",
        endpoint=endpoint,
        access_key="ak",
        secret_key="sk",
        multipart_threshold=1 << 16,  # 64 KiB so tests exercise multipart
        download_chunk_bytes=1 << 20,
        download_concurrency=4,
    )
    yield storage, state
    srv.shutdown()


def test_crud_roundtrip(s3):
    storage, _ = s3
    storage.put_object("a/b/file.json", b'{"x": 1}')
    assert storage.get_object("a/b/file.json") == b'{"x": 1}'
    assert storage.head("a/b/file.json").size == 8
    assert storage.exists("a/b/file.json")
    storage.delete_object("a/b/file.json")
    assert not storage.exists("a/b/file.json")
    with pytest.raises(NoSuchKey):
        storage.get_object("a/b/file.json")


def test_list_prefix_and_dirs(s3):
    storage, _ = s3
    for k in ("s/date=1/x.parquet", "s/date=1/y.parquet", "s/date=2/z.parquet", "t/other"):
        storage.put_object(k, b"data")
    keys = [m.key for m in storage.list_prefix("s/")]
    assert keys == ["s/date=1/x.parquet", "s/date=1/y.parquet", "s/date=2/z.parquet"]
    assert storage.list_dirs("s") == ["date=1", "date=2"]


def test_list_pagination(s3):
    storage, state = s3
    for i in range(25):
        storage.put_object(f"pg/k{i:03d}", b"x")
    # force tiny pages through the mock by patching max-keys via monkey query:
    # the client paginates on IsTruncated/NextContinuationToken
    import parseable_tpu.storage.s3 as s3mod

    orig = storage._request

    def patched(method, key="", query=None, **kw):
        if query and query.get("list-type") == "2":
            query = dict(query, **{"max-keys": "10"})
        return orig(method, key, query, **kw)

    storage._request = patched
    keys = [m.key for m in storage.list_prefix("pg/")]
    assert len(keys) == 25
    storage._request = orig


def test_multipart_upload_and_ranged_download(s3, tmp_path):
    storage, state = s3
    big = bytes(range(256)) * 2048  # 512 KiB > 64 KiB threshold
    src = tmp_path / "big.bin"
    src.write_bytes(big)
    storage.upload_file("mp/big.bin", src)
    # stored via multipart (mock concatenates parts)
    assert state.objects["mp/big.bin"] == big
    # download via a smaller chunk size to force parallel ranged GETs
    storage.download_chunk_bytes = 1 << 17
    dest = tmp_path / "out.bin"
    storage.download_file("mp/big.bin", dest)
    assert dest.read_bytes() == big


def test_delete_prefix_batch(s3):
    storage, state = s3
    for i in range(5):
        storage.put_object(f"dp/day=1/f{i}", b"x")
    storage.put_object("dp/day=2/keep", b"x")
    storage.delete_prefix("dp/day=1/")
    assert [m.key for m in storage.list_prefix("dp/")] == ["dp/day=2/keep"]


def test_full_pipeline_on_s3(tmp_path):
    """ingest -> staging -> parquet -> S3 upload -> catalog -> query."""
    srv, endpoint, state = serve()
    try:
        from parseable_tpu.config import Options, StorageOptions
        from parseable_tpu.core import Parseable
        from parseable_tpu.event.json_format import JsonEvent
        from parseable_tpu.query.session import QuerySession

        opts = Options()
        opts.local_staging_path = tmp_path / "staging"
        storage_opts = StorageOptions(
            backend="s3-store",
            bucket="testbucket",
            region="us-east-1",
            endpoint_url=endpoint,
            access_key="ak",
            secret_key="sk",
        )
        p = Parseable(opts, storage_opts)
        stream = p.create_stream_if_not_exists("s3web")
        records = [{"host": f"h{i % 3}", "v": float(i)} for i in range(300)]
        ev = JsonEvent(records, "s3web").into_event(stream.metadata)
        ev.process(stream, commit_schema=p.commit_schema)
        p.local_sync(shutdown=True)
        p.sync_all_streams()

        # parquet + catalog objects landed in the mock bucket
        assert any(k.endswith(".parquet") for k in state.objects)
        assert any(k.endswith("manifest.json") for k in state.objects)
        fmt = p.metastore.get_stream_json("s3web")
        assert fmt.stats.events == 300

        # query reads parquet back from S3
        sess = QuerySession(p, engine="cpu")
        res = sess.query("SELECT host, count(*) c, sum(v) s FROM s3web GROUP BY host ORDER BY host")
        rows = res.to_json_rows()
        assert [r["c"] for r in rows] == [100, 100, 100]

        # restart bootstrap: a fresh instance discovers the stream from S3
        opts2 = Options()
        opts2.local_staging_path = tmp_path / "staging2"
        p2 = Parseable(opts2, storage_opts)
        p2.load_streams_from_storage()
        res2 = QuerySession(p2, engine="cpu").query("SELECT count(*) FROM s3web")
        assert res2.to_json_rows()[0]["count(*)"] == 300
        p.shutdown()  # pools must not outlive the test (psan-thread-leak)
        p2.shutdown()
    finally:
        srv.shutdown()


def test_hot_tier_chunked_download_on_s3(tmp_path):
    """Hot tier reconcile downloads manifests' parquet from S3 via the
    chunked path and honors the size budget."""
    srv, endpoint, state = serve()
    try:
        from parseable_tpu.config import Options, StorageOptions
        from parseable_tpu.core import Parseable
        from parseable_tpu.event.json_format import JsonEvent
        from parseable_tpu.storage.hottier import HotTierManager

        opts = Options()
        opts.local_staging_path = tmp_path / "staging"
        opts.hot_tier_storage_path = tmp_path / "hottier"
        storage_opts = StorageOptions(
            backend="s3-store", bucket="testbucket", endpoint_url=endpoint,
            access_key="ak", secret_key="sk",
        )
        p = Parseable(opts, storage_opts)
        stream = p.create_stream_if_not_exists("hts3")
        ev = JsonEvent([{"v": float(i)} for i in range(2000)], "hts3").into_event(stream.metadata)
        ev.process(stream, commit_schema=p.commit_schema)
        p.local_sync(shutdown=True)
        p.sync_all_streams()

        mgr = HotTierManager(p, tmp_path / "hottier")
        mgr.set_budget("hts3", 50 * 1024 * 1024)
        mgr.reconcile("hts3")
        local = list((tmp_path / "hottier").rglob("*.parquet"))
        assert local, "hot tier downloaded nothing"
    finally:
        srv.shutdown()
