"""Regressions for the device-path findings dlint surfaced and this PR
fixed — each test names the rule it pins down.

The static gate proves the *shape* of the discipline (annotated jit sites
riding a declared cache, syncs routed through declared boundaries, priced
transfers); these tests prove the *behavior*: warm queries build zero new
XLA programs, every readback and LUT ship lands in the byte accounting the
adaptive router trusts, and the program-cache traffic is consumed from
stats.stages.programs.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pytest

from parseable_tpu.query import executor_tpu as ET
from parseable_tpu.query.planner import plan as build_plan
from parseable_tpu.query.sql import parse_sql
from parseable_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _no_adaptive(monkeypatch):
    # deterministic device routing: the adaptive gate must not shunt test
    # blocks to the host path these regressions exist to exercise
    monkeypatch.setenv("P_TPU_ADAPTIVE", "0")


def table(n=6_000, seed=0, groups=8):
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "g": pa.array([f"g{int(x)}" for x in rng.integers(0, groups, n)]),
            "v": pa.array(rng.random(n) * 100),
        }
    )


def run_tpu(sql: str, tables: list[pa.Table]):
    lp = build_plan(parse_sql(sql))
    ex = ET.TpuQueryExecutor(lp)
    out = ex.execute(iter(tables)).to_pylist()
    return out, ex


# ------------------------------------------------- jit-cache-discipline


def test_warm_agg_query_builds_zero_new_programs():
    """jit-cache-discipline: the dense-agg jit site rides _PROGRAM_CACHE —
    a warm query with identical shape classes must compile NOTHING new
    (this is the per-call-jit failure mode the rule and the P_DLINT
    tripwire both exist to block)."""
    t = table()
    sql = "SELECT g, count(v) c, avg(v) a FROM t GROUP BY g ORDER BY g"
    cold, _ = run_tpu(sql, [t])  # builds whatever keys are missing
    before = ET.PROGRAM_BUILDS[0]
    warm, ex = run_tpu(sql, [t])
    assert warm == cold
    assert ET.PROGRAM_BUILDS[0] == before, "warm query rebuilt a program"
    assert ex.route_stats["programs_built"] == 0
    assert ex.route_stats["programs_reused"] > 0
    assert ex.route_stats["recompiles"] == 0


def test_warm_topk_query_builds_zero_new_programs():
    """jit-cache-discipline, executor.topk program family."""
    t = table()
    sql = "SELECT g, count(v) c FROM t GROUP BY g ORDER BY c DESC LIMIT 3"
    cold, _ = run_tpu(sql, [t])
    before = ET.PROGRAM_BUILDS[0]
    warm, ex = run_tpu(sql, [t])
    assert warm == cold
    assert ET.PROGRAM_BUILDS[0] == before
    assert ex.route_stats["programs_built"] == 0
    assert ex.route_stats["recompiles"] == 0


def test_note_program_build_detects_rebuilt_keys():
    """The accounting under the tripwire's metric: rebuilding an
    already-built (program, key) ticks tpu_recompiles_total{program} and
    the route recompile counter; a fresh key does not."""
    program = "regress.note"

    def sample():
        return (
            metrics.REGISTRY.get_sample_value(
                "parseable_tpu_recompiles_total", {"program": program}
            )
            or 0.0
        )

    stats = {}
    base = sample()
    ET._note_program_build(program, ("k", 1), stats)
    assert sample() == base and stats.get("recompiles", 0) == 0
    ET._note_program_build(program, ("k", 2), stats)
    assert sample() == base  # second DISTINCT key: still no recompile
    ET._note_program_build(program, ("k", 1), stats)
    assert sample() == base + 1
    assert stats["recompiles"] == 1
    assert stats["programs_built"] == 3


# ------------------------------------------------------------- host-sync


def test_select_readback_is_priced_d2h():
    """host-sync: the filter-mask readback flows through _timed_readback
    (the declared sync boundary), so its bytes land in d2h accounting
    instead of an invisible np.asarray stall."""
    t = table()
    out, ex = run_tpu("SELECT g, v FROM t WHERE v > 50", [t])
    assert out, "filter should select roughly half the rows"
    assert ex.route_stats["d2h_bytes"] > 0


def test_timed_readback_prices_wire_bytes_at_device_width():
    """host-sync: wire bytes are priced at the DEVICE dtype width (capped
    at 4 — the layer is f32/int32/bool end to end) even when the host
    target is f64, and `dtype=None` keeps the device dtype."""
    jnp = pytest.importorskip("jax.numpy")
    x = jnp.ones((16,), dtype=jnp.float32)
    stats = {"d2h_bytes": 0}
    arr = ET._timed_readback(x, stats)
    assert arr.dtype == np.float64  # host representation promoted
    assert stats["d2h_bytes"] == 16 * 4  # ...but priced as f32 on the wire

    native = ET._timed_readback(jnp.arange(8, dtype=jnp.int32), None, dtype=None)
    assert native.dtype == np.int32


# ---------------------------------------------------- transfer-discipline


def test_group_lut_and_accumulator_ships_are_priced_h2d():
    """transfer-discipline: the group-LUT and accumulator device_put sites
    tick h2d route bytes and the tpu_bytes_to_device{op} counter —
    un-priced ships would starve the link EWMA the adaptive router reads."""

    def op_total(op):
        return (
            metrics.REGISTRY.get_sample_value(
                "parseable_tpu_bytes_to_device_total", {"op": op}
            )
            or 0.0
        )

    before = op_total("lut") + op_total("acc")
    t = table(seed=7)
    _, ex = run_tpu("SELECT g, sum(v) s FROM t GROUP BY g ORDER BY g", [t])
    assert ex.route_stats["h2d_bytes"] > 0
    assert op_total("lut") + op_total("acc") > before


# ------------------------------------------------------- stages.programs


def test_stages_programs_consumed_from_session(parseable):
    """The wlint stages-contract consumer for the new stages.programs
    entry: a TPU-engine query reports built/reused/recompiles (recompiles
    pinned at 0 — the tripwire budget), and the CPU engine reports None."""
    from datetime import datetime, timedelta

    from parseable_tpu import DEFAULT_TIMESTAMP_KEY
    from parseable_tpu.event import Event
    from parseable_tpu.query.session import QuerySession

    p = parseable
    stream = p.create_stream_if_not_exists("dlint_logs")
    rng = np.random.default_rng(3)
    base = datetime(2024, 6, 1)
    n = 4_000
    tbl = pa.table(
        {
            DEFAULT_TIMESTAMP_KEY: pa.array(
                [base + timedelta(milliseconds=int(i)) for i in range(n)],
                pa.timestamp("ms"),
            ),
            "host": pa.array([f"h{int(x)}" for x in rng.integers(0, 8, n)]),
            "bytes": pa.array(rng.random(n) * 100),
        }
    )
    for b in tbl.to_batches():
        Event(
            stream_name="dlint_logs", rb=b, origin_size=1, is_first_event=True,
            parsed_timestamp=base,
        ).process(stream, commit_schema=p.commit_schema)
    p.local_sync(shutdown=True)
    p.sync_all_streams()

    sql = "SELECT host, count(*) c FROM dlint_logs GROUP BY host ORDER BY host"
    res = QuerySession(p, engine="tpu").query(sql)
    prog = res.stats["stages"]["programs"]
    assert prog is not None
    assert set(prog) == {"built", "reused", "recompiles"}
    assert prog["built"] + prog["reused"] > 0
    assert prog["recompiles"] == 0

    cpu = QuerySession(p, engine="cpu").query(sql)
    assert cpu.stats["stages"]["programs"] is None
