"""HTTP API tests: ingest -> query end-to-end over the real server.

The reference covers this surface with docker-compose + the external quest
harness (SURVEY §4); here aiohttp's test client drives the same flows
in-process.
"""

import asyncio
import base64
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from parseable_tpu.config import Mode, Options, StorageOptions
from parseable_tpu.core import Parseable
from parseable_tpu.server.app import ServerState, build_app


def make_state(tmp_path, mode=Mode.ALL):
    opts = Options()
    opts.local_staging_path = tmp_path / "staging"
    opts.mode = mode
    p = Parseable(opts, StorageOptions(backend="local-store", root=tmp_path / "data"))
    return ServerState(p)


AUTH = {"Authorization": "Basic " + base64.b64encode(b"admin:admin").decode()}


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


async def with_client(state, fn, stop=True):
    """Run `fn` against a live test client; by default the ServerState is
    stopped afterwards so its pools (ingest/query workers, sync/upload)
    never outlive the test — psan's thread-leak detector enforces this.
    Pass stop=False when the test asserts pre-stop staging state or stops
    explicitly itself."""
    app = build_app(state)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()
        if stop:
            state.stop()


def test_health_and_about(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        assert (await client.get("/api/v1/liveness")).status == 200
        assert (await client.get("/api/v1/readiness")).status == 200
        r = await client.get("/api/v1/about", headers=AUTH)
        assert r.status == 200
        body = await r.json()
        assert body["mode"] == "All"

    run(with_client(state, fn))


def test_auth_required_and_rejected(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        assert (await client.get("/api/v1/logstream")).status == 401
        bad = {"Authorization": "Basic " + base64.b64encode(b"admin:wrong").decode()}
        assert (await client.get("/api/v1/logstream", headers=bad)).status == 401

    run(with_client(state, fn))


def test_ingest_query_roundtrip(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        records = [{"host": f"h{i%2}", "status": 200 if i % 3 else 500} for i in range(30)]
        r = await client.post(
            "/api/v1/ingest", json=records, headers={**AUTH, "X-P-Stream": "api"}
        )
        assert r.status == 200, await r.text()
        state.p.local_sync(shutdown=True)
        state.p.sync_all_streams()

        r = await client.post(
            "/api/v1/query",
            json={"query": "SELECT host, count(*) c FROM api GROUP BY host ORDER BY host"},
            headers=AUTH,
        )
        assert r.status == 200, await r.text()
        rows = await r.json()
        assert rows == [{"host": "h0", "c": 15}, {"host": "h1", "c": 15}]

        # stats + schema + info + list
        r = await client.get("/api/v1/logstream", headers=AUTH)
        assert [s["name"] for s in await r.json()] == ["api"]
        r = await client.get("/api/v1/logstream/api/schema", headers=AUTH)
        names = [f["name"] for f in (await r.json())["fields"]]
        assert "host" in names and "p_timestamp" in names
        r = await client.get("/api/v1/logstream/api/stats", headers=AUTH)
        stats = await r.json()
        assert stats["ingestion"]["count"] == 30

    run(with_client(state, fn))


def test_ingest_missing_stream_header(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        r = await client.post("/api/v1/ingest", json=[{"a": 1}], headers=AUTH)
        assert r.status == 400

    run(with_client(state, fn))


def test_otel_logs_ingest(tmp_path):
    state = make_state(tmp_path)
    payload = {
        "resourceLogs": [
            {
                "resource": {"attributes": [{"key": "service.name", "value": {"stringValue": "svc"}}]},
                "scopeLogs": [
                    {
                        "scope": {"name": "lib"},
                        "logRecords": [
                            {
                                "timeUnixNano": "1714521600000000000",
                                "severityNumber": 9,
                                "body": {"stringValue": "hello"},
                                "attributes": [{"key": "k", "value": {"intValue": "7"}}],
                            }
                        ],
                    }
                ],
            }
        ]
    }

    async def fn(client):
        r = await client.post("/v1/logs", json=payload, headers=AUTH)
        assert r.status == 200, await r.text()
        state.p.local_sync(shutdown=True)
        r = await client.post(
            "/api/v1/query",
            json={"query": "SELECT body, severity_text, k FROM \"otel-logs\""},
            headers=AUTH,
        )
        rows = await r.json()
        assert rows[0]["body"] == "hello"
        assert rows[0]["severity_text"] == "SEVERITY_NUMBER_INFO"
        assert rows[0]["k"] == 7

    run(with_client(state, fn))


def test_rbac_user_lifecycle(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        # create a reader role + user
        r = await client.put(
            "/api/v1/role/readers",
            json=[{"privilege": "reader", "resource": {"stream": "api"}}],
            headers=AUTH,
        )
        assert r.status == 200
        r = await client.post("/api/v1/user/alice", json={"roles": ["readers"]}, headers=AUTH)
        assert r.status == 200
        password = await r.json()
        alice = {"Authorization": "Basic " + base64.b64encode(f"alice:{password}".encode()).decode()}
        # alice can list streams but cannot ingest
        assert (await client.get("/api/v1/logstream", headers=alice)).status == 200
        r = await client.post(
            "/api/v1/ingest", json=[{"a": 1}], headers={**alice, "X-P-Stream": "api"}
        )
        assert r.status == 403
        # delete user -> auth fails
        assert (await client.delete("/api/v1/user/alice", headers=AUTH)).status == 200
        assert (await client.get("/api/v1/logstream", headers=alice)).status == 401

    run(with_client(state, fn))


def test_alert_crud_and_eval(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        # alert windows end at the truncated current minute (reference
        # parse_human_time semantics), so events must be >1 minute old
        from datetime import UTC, datetime, timedelta

        import pyarrow as pa

        from parseable_tpu import DEFAULT_TIMESTAMP_KEY
        from parseable_tpu.event import Event

        stream = state.p.create_stream_if_not_exists("errs")
        old = datetime.now(UTC) - timedelta(minutes=2)
        batch = pa.RecordBatch.from_pydict(
            {
                DEFAULT_TIMESTAMP_KEY: pa.array(
                    [old.replace(tzinfo=None)] * 5, pa.timestamp("ms")
                ),
                "status": pa.array([500.0] * 5),
            }
        )
        Event("errs", batch, parsed_timestamp=old, is_first_event=True).process(
            stream, commit_schema=state.p.commit_schema
        )
        alert = {
            "title": "too many errors",
            "stream": "errs",
            "threshold_config": {"agg": "count", "operator": ">", "value": 3},
            "eval_frequency": 1,
        }
        r = await client.post("/api/v1/alerts", json=alert, headers=AUTH)
        assert r.status == 200, await r.text()
        created = await r.json()
        # invalid alert rejected
        r = await client.post("/api/v1/alerts", json={"title": "x"}, headers=AUTH)
        assert r.status == 400
        # evaluate
        from parseable_tpu.alerts import alert_tick

        alert_tick(state)
        rec = state.p.metastore.get_document("alert_state", created["id"])
        assert rec["state"] == "triggered"
        assert rec["actual"] == 5

    run(with_client(state, fn))


def test_dashboards_crud(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        r = await client.post(
            "/api/v1/dashboards", json={"name": "ops", "tiles": []}, headers=AUTH
        )
        doc = await r.json()
        r = await client.get(f"/api/v1/dashboards/{doc['id']}", headers=AUTH)
        assert (await r.json())["name"] == "ops"
        r = await client.get("/api/v1/dashboards", headers=AUTH)
        assert len(await r.json()) == 1
        assert (await client.delete(f"/api/v1/dashboards/{doc['id']}", headers=AUTH)).status == 200

    run(with_client(state, fn))


def test_retention_endpoint_and_apply(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        r = await client.post(
            "/api/v1/ingest", json=[{"a": 1}], headers={**AUTH, "X-P-Stream": "old"}
        )
        assert r.status == 200
        r = await client.put(
            "/api/v1/logstream/old/retention",
            json=[{"description": "d", "action": "delete", "duration": "30d"}],
            headers=AUTH,
        )
        assert r.status == 200, await r.text()
        r = await client.put(
            "/api/v1/logstream/old/retention",
            json=[{"action": "nonsense", "duration": "30d"}],
            headers=AUTH,
        )
        assert r.status == 400

    run(with_client(state, fn))


def test_internal_staging_endpoint(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        await client.post(
            "/api/v1/ingest", json=[{"a": 1.5}], headers={**AUTH, "X-P-Stream": "live"}
        )
        r = await client.get("/api/v1/internal/staging/live", headers=AUTH)
        assert r.status == 200
        body = await r.read()
        import io

        import pyarrow.ipc as ipc

        batches = list(ipc.open_stream(io.BytesIO(body)))
        assert sum(b.num_rows for b in batches) == 1

    run(with_client(state, fn))


def test_session_login_and_bearer(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        r = await client.get("/api/v1/login", headers=AUTH)
        assert r.status == 200
        token = (await r.json())["token"]
        bearer = {"Authorization": f"Bearer {token}"}
        assert (await client.get("/api/v1/logstream", headers=bearer)).status == 200
        assert (
            await client.get("/api/v1/logstream", headers={"Authorization": "Bearer nope"})
        ).status == 401

    run(with_client(state, fn))


def test_put_user_conflict(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        assert (await client.post("/api/v1/user/carol", headers=AUTH)).status == 200
        r = await client.post("/api/v1/user/carol", headers=AUTH)
        assert r.status == 400  # no silent password reset

    run(with_client(state, fn))


def test_static_schema_rejects_extra_fields(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        r = await client.put(
            "/api/v1/logstream/strict",
            json={"fields": [{"name": "a", "data_type": "int"}]},
            headers={**AUTH, "X-P-Static-Schema-Flag": "true"},
        )
        assert r.status == 200, await r.text()
        ok = await client.post(
            "/api/v1/ingest", json=[{"a": 1}], headers={**AUTH, "X-P-Stream": "strict"}
        )
        assert ok.status == 200
        bad = await client.post(
            "/api/v1/ingest", json=[{"a": 1, "b": "x"}], headers={**AUTH, "X-P-Stream": "strict"}
        )
        assert bad.status == 400
        body = await bad.json()
        assert "static schema" in body["error"]
        # schema unchanged
        r = await client.get("/api/v1/logstream/strict/schema", headers=AUTH)
        names = [f["name"] for f in (await r.json())["fields"]]
        assert "b" not in names

    run(with_client(state, fn))


def test_update_stream_custom_partition(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        await client.post(
            "/api/v1/ingest", json=[{"region": "us"}], headers={**AUTH, "X-P-Stream": "upd"}
        )
        r = await client.put(
            "/api/v1/logstream/upd",
            headers={**AUTH, "X-P-Update-Stream": "true", "X-P-Custom-Partition": "region"},
        )
        assert r.status == 200
        assert (await r.json())["message"] == "updated stream upd"
        assert state.p.get_stream("upd").metadata.custom_partition == "region"
        # time partition change rejected
        r = await client.put(
            "/api/v1/logstream/upd",
            headers={**AUTH, "X-P-Update-Stream": "true", "X-P-Time-Partition": "ts"},
        )
        assert r.status == 400

    run(with_client(state, fn))


def test_counts_bins_align_to_start(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        from datetime import UTC, datetime, timedelta

        import pyarrow as pa

        from parseable_tpu import DEFAULT_TIMESTAMP_KEY
        from parseable_tpu.event import Event

        stream = state.p.create_stream_if_not_exists("hist")
        old = datetime.now(UTC) - timedelta(minutes=30)
        batch = pa.RecordBatch.from_pydict(
            {
                DEFAULT_TIMESTAMP_KEY: pa.array(
                    [(old + timedelta(minutes=i)).replace(tzinfo=None) for i in range(20)],
                    pa.timestamp("ms"),
                ),
                "v": pa.array([1.0] * 20),
            }
        )
        Event("hist", batch, parsed_timestamp=old, is_first_event=True).process(
            stream, commit_schema=state.p.commit_schema
        )
        r = await client.post(
            "/api/v1/counts",
            json={"stream": "hist", "startTime": "1h", "endTime": "now", "numBins": 6},
            headers=AUTH,
        )
        assert r.status == 200, await r.text()
        records = (await r.json())["records"]
        assert sum(rec["count"] for rec in records) == 20  # keys aligned

    run(with_client(state, fn))


def test_internal_staging_requires_query_permission(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        await client.post(
            "/api/v1/ingest", json=[{"a": 1}], headers={**AUTH, "X-P-Stream": "secret"}
        )
        # ingest-only user cannot dump staging
        await client.put(
            "/api/v1/role/pusher",
            json=[{"privilege": "ingestor", "resource": {"stream": "other"}}],
            headers=AUTH,
        )
        r = await client.post("/api/v1/user/ing", json={"roles": ["pusher"]}, headers=AUTH)
        pw = await r.json()
        ing = {"Authorization": "Basic " + base64.b64encode(f"ing:{pw}".encode()).decode()}
        r = await client.get("/api/v1/internal/staging/secret", headers=ing)
        assert r.status == 403

    run(with_client(state, fn))


def test_streaming_query(tmp_path):
    """NDJSON streaming (reference: query.rs:325-407): rows arrive in
    chunks, optional fields line first."""
    state = make_state(tmp_path)

    async def fn(client):
        r = await client.post(
            "/api/v1/ingest",
            json=[{"a": i} for i in range(30)],
            headers={**AUTH, "X-P-Stream": "s1"},
        )
        assert r.status == 200
        r = await client.post(
            "/api/v1/query",
            json={
                "query": "select a from s1 limit 10",
                "startTime": "1h",
                "endTime": "now",
                "streaming": True,
                "fields": True,
            },
            headers=AUTH,
        )
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("application/x-ndjson")
        lines = [json.loads(l) for l in (await r.text()).strip().splitlines()]
        assert lines[0] == {"fields": ["a"]}
        rows = [rec for l in lines[1:] for rec in l["records"]]
        assert len(rows) == 10

    run(with_client(state, fn))


def test_query_timeout_maps_to_504(tmp_path):
    state = make_state(tmp_path)
    state.p.options.query_timeout_secs = -1  # instantly expired deadline

    async def fn(client):
        await client.post(
            "/api/v1/ingest", json=[{"a": 1}], headers={**AUTH, "X-P-Stream": "s2"}
        )
        r = await client.post(
            "/api/v1/query",
            json={"query": "select a, count(*) from s2 group by a",
                  "startTime": "1h", "endTime": "now"},
            headers=AUTH,
        )
        assert r.status == 504

    run(with_client(state, fn))


def test_ui_static_serving(tmp_path):
    """P_UI_DIR serves the console bundle at / without auth (reference:
    build.rs embedded console; here an external dir)."""
    ui = tmp_path / "console"
    (ui / "assets").mkdir(parents=True)
    (ui / "index.html").write_text("<html>console</html>")
    (ui / "assets" / "app.js").write_text("// js")
    state = make_state(tmp_path)
    state.p.options.ui_dir = ui

    async def fn(client):
        r = await client.get("/")  # no auth
        assert r.status == 200
        assert "console" in await r.text()
        r = await client.get("/assets/app.js")
        assert r.status == 200
        # API still requires auth
        r = await client.get("/api/v1/logstream")
        assert r.status == 401

    run(with_client(state, fn))


def test_ui_spa_fallback_and_missing_index(tmp_path):
    state = make_state(tmp_path)
    # dir without index.html -> console disabled, / is a plain 404/401 surface
    broken = tmp_path / "broken-ui"
    broken.mkdir()
    state.p.options.ui_dir = broken

    async def fn(client):
        r = await client.get("/")
        assert r.status == 404  # no route registered; not a 500

    run(with_client(state, fn))

    # proper bundle: deep links serve the shell, API stays authed
    ui = tmp_path / "ui"
    (ui / "assets").mkdir(parents=True)
    (ui / "index.html").write_text("<html>shell</html>")
    state2 = make_state(tmp_path / "s2")
    state2.p.options.ui_dir = ui

    async def fn2(client):
        r = await client.get("/explore/streams")  # SPA deep link, no auth
        assert r.status == 200
        assert "shell" in await r.text()
        r = await client.get("/api/v1/logstream")
        assert r.status == 401

    run(with_client(state2, fn2))


def test_logout_schema_detect_alert_controls(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        # login -> token works -> logout -> token dead
        r = await client.get("/api/v1/login", headers=AUTH)
        token = (await r.json())["token"]
        bearer = {"Authorization": f"Bearer {token}"}
        assert (await client.get("/api/v1/logstream", headers=bearer)).status == 200
        assert (await client.get("/api/v1/logout", headers=bearer)).status == 200
        assert (await client.get("/api/v1/logstream", headers=bearer)).status == 401

        # schema detect: nested payload -> flattened inferred fields
        r = await client.post(
            "/api/v1/logstream/schema/detect",
            json=[{"a": 1, "nested": {"b": "x"}, "event_time": "2024-05-01T10:00:00Z"}],
            headers=AUTH,
        )
        assert r.status == 200, await r.text()
        fields = {f["name"]: f["data_type"] for f in (await r.json())["fields"]}
        assert fields["a"] == "double"
        assert fields["nested_b"] == "string"
        assert fields["event_time"].startswith("timestamp")

        # alert enable/disable + manual evaluation
        await client.post(
            "/api/v1/ingest", json=[{"status": 500}] * 5, headers={**AUTH, "X-P-Stream": "ev"}
        )
        alert = {
            "id": "al1",
            "title": "manual",
            "stream": "ev",
            "threshold_config": {"agg": "count", "operator": ">", "value": 3},
        }
        r = await client.post("/api/v1/alerts", json=alert, headers=AUTH)
        assert r.status == 200, await r.text()
        alert_id = (await r.json())["id"]
        r = await client.put(f"/api/v1/alerts/{alert_id}/evaluate_alert", headers=AUTH)
        assert r.status == 200, await r.text()
        assert (await r.json())["state"] == "triggered"
        # a manual evaluation records real state (MTTR machine ran)
        r = await client.get(f"/api/v1/alerts/{alert_id}/state", headers=AUTH)
        st = await r.json()
        assert st["state"] == "triggered" and st["incidents"] == 1
        r = await client.put(f"/api/v1/alerts/{alert_id}/disable", headers=AUTH)
        assert r.status == 200
        doc = state.p.metastore.get_document("alerts", alert_id)
        assert doc["state"] == "disabled"
        r = await client.put(f"/api/v1/alerts/{alert_id}/enable", headers=AUTH)
        assert (await r.json())["message"] == "alert enabled"

        # dashboards: add_tile + list_tags
        r = await client.post(
            "/api/v1/dashboards",
            json={"title": "ops", "tags": ["prod", "web"]},
            headers=AUTH,
        )
        dash_id = (await r.json())["id"]
        r = await client.put(
            f"/api/v1/dashboards/{dash_id}/add_tile",
            json={"title": "errors", "query": "select count(*) from ev"},
            headers=AUTH,
        )
        assert r.status == 200
        assert len((await r.json())["tiles"]) == 1
        r = await client.get("/api/v1/dashboards/list_tags", headers=AUTH)
        assert await r.json() == ["prod", "web"]

    run(with_client(state, fn))


def test_kinesis_firehose_ingest(tmp_path):
    """Kinesis Firehose payloads decode base64 records and enrich with
    requestId/timestamp (reference: handlers/http/kinesis.rs)."""
    import base64 as b64

    state = make_state(tmp_path)

    async def fn(client):
        payload = {
            "requestId": "req-1",
            "timestamp": 1714557600000,
            "records": [
                {"data": b64.b64encode(json.dumps({"level": "info", "n": 1}).encode()).decode()},
                {"data": b64.b64encode(json.dumps({"level": "error", "n": 2}).encode()).decode()},
                {"data": b64.b64encode(b'"bare string"').decode()},
            ],
        }
        r = await client.post(
            "/api/v1/ingest",
            json=payload,
            headers={**AUTH, "X-P-Stream": "kin", "X-P-Log-Source": "kinesis"},
        )
        assert r.status == 200, await r.text()
        r = await client.post(
            "/api/v1/query",
            json={
                "query": "SELECT level, requestId, message, n FROM kin",
                "startTime": "1h",
                "endTime": "now",
            },
            headers=AUTH,
        )
        rows = await r.json()
        assert len(rows) == 3
        by_level = {r.get("level"): r for r in rows}
        assert by_level["info"]["requestId"] == "req-1"
        assert by_level["error"]["n"] == 2
        assert by_level[None]["message"] == "bare string"

        # malformed base64 -> clean 400
        r = await client.post(
            "/api/v1/ingest",
            json={"records": [{"data": "!!!notb64"}]},
            headers={**AUTH, "X-P-Stream": "kin", "X-P-Log-Source": "kinesis"},
        )
        assert r.status == 400

    run(with_client(state, fn))


def test_stats_date_param_and_shutdown_drain(tmp_path):
    """?date= filters stats to a day's manifest items (reference:
    get_stats_date); ServerState.stop() drains staging to the store."""
    from datetime import UTC, datetime

    state = make_state(tmp_path)

    async def fn(client):
        r = await client.post(
            "/api/v1/ingest", json=[{"a": i} for i in range(40)],
            headers={**AUTH, "X-P-Stream": "dated"},
        )
        assert r.status == 200

    run(with_client(state, fn))
    # drain on shutdown: nothing was uploaded yet; stop() must flush
    state.stop()
    fmts = state.p.metastore.get_all_stream_jsons("dated")
    assert sum(f.stats.events for f in fmts) == 40

    # per-date stats: today's partition has the rows; a bogus date has none
    state2 = make_state(tmp_path / "v2")
    state2.p = state.p  # same store

    async def fn2(client):
        today = datetime.now(UTC).date().isoformat()
        r = await client.get(f"/api/v1/logstream/dated/stats?date={today}", headers=AUTH)
        assert (await r.json())["ingestion"]["count"] == 40
        r = await client.get("/api/v1/logstream/dated/stats?date=1999-01-01", headers=AUTH)
        assert (await r.json())["ingestion"]["count"] == 0
        r = await client.get("/api/v1/logstream/dated/stats", headers=AUTH)
        assert (await r.json())["ingestion"]["count"] == 40

    run(with_client(state2, fn2))


def test_notification_state_and_policy_endpoints(tmp_path):
    state = make_state(tmp_path)

    async def fn(client):
        alert = {
            "id": "mute1", "title": "m", "stream": "s",
            "threshold_config": {"agg": "count", "operator": ">", "value": 1},
        }
        r = await client.post("/api/v1/alerts", json=alert, headers=AUTH)
        assert r.status == 200

        # mute indefinitely, then un-mute, then bad state -> 400
        r = await client.put(
            "/api/v1/alerts/mute1/update_notification_state",
            json={"state": "indefinite"}, headers=AUTH,
        )
        assert r.status == 200
        doc = state.p.metastore.get_document("alerts", "mute1")
        assert doc["notification_state"] == "indefinite"
        r = await client.put(
            "/api/v1/alerts/mute1/update_notification_state",
            json={"state": "notify"}, headers=AUTH,
        )
        assert r.status == 200
        r = await client.put(
            "/api/v1/alerts/mute1/update_notification_state",
            json={"state": "whenever"}, headers=AUTH,
        )
        assert r.status == 400

        # outbound policy CRUD + CIDR validation
        r = await client.put(
            "/api/v1/alert-target-policy",
            json={"denied_cidrs": ["10.0.0.0/8"], "allowed_domains": ["hooks.example.com"]},
            headers=AUTH,
        )
        assert r.status == 200
        r = await client.get("/api/v1/alert-target-policy", headers=AUTH)
        policy = await r.json()
        assert policy["denied_cidrs"] == ["10.0.0.0/8"]
        r = await client.put(
            "/api/v1/alert-target-policy", json={"denied_cidrs": ["not-a-cidr"]}, headers=AUTH
        )
        assert r.status == 400

    run(with_client(state, fn))
