"""OTel flattener semantics (reference: src/otel/{logs,metrics,traces}.rs —
SURVEY: "port semantics exactly"): all five metric kinds, span
events/links/enum enrichment, and end-to-end OTLP ingest -> query."""

import json

from parseable_tpu.otel.logs import flatten_otel_logs
from parseable_tpu.otel.metrics import flatten_otel_metrics
from parseable_tpu.otel.traces import flatten_otel_traces

RESOURCE = {
    "attributes": [
        {"key": "service.name", "value": {"stringValue": "checkout"}},
    ]
}
SCOPE = {"name": "meter", "version": "1.0"}


def _metric_payload(metric: dict) -> dict:
    return {
        "resourceMetrics": [
            {"resource": RESOURCE, "scopeMetrics": [{"scope": SCOPE, "metrics": [metric]}]}
        ]
    }


def test_gauge_and_sum():
    rows = flatten_otel_metrics(
        _metric_payload(
            {
                "name": "cpu.util",
                "unit": "%",
                "gauge": {
                    "dataPoints": [
                        {
                            "asDouble": 42.5,
                            "timeUnixNano": "1714557600000000000",
                            "attributes": [{"key": "core", "value": {"intValue": "3"}}],
                        }
                    ]
                },
            }
        )
    )
    assert len(rows) == 1
    r = rows[0]
    assert r["metric_type"] == "gauge"
    assert r["metric_name"] == "cpu.util"
    assert r["resource_service.name"] == "checkout"
    assert r["core"] == 3

    rows = flatten_otel_metrics(
        _metric_payload(
            {
                "name": "requests.total",
                "sum": {
                    "isMonotonic": True,
                    "aggregationTemporality": 2,
                    "dataPoints": [{"asInt": "128", "timeUnixNano": "1714557600000000000"}],
                },
            }
        )
    )
    r = rows[0]
    assert r["metric_type"] == "sum"
    assert r["sum_is_monotonic"] is True
    assert r["sum_aggregation_temporality"] == 2
    assert "CUMULATIVE" in r["sum_aggregation_temporality_description"].upper()


def test_histogram_exponential_and_summary():
    rows = flatten_otel_metrics(
        _metric_payload(
            {
                "name": "latency",
                "histogram": {
                    "aggregationTemporality": 1,
                    "dataPoints": [
                        {
                            "count": "7",
                            "sum": 99.5,
                            "min": 1.0,
                            "max": 50.0,
                            "bucketCounts": ["1", "4", "2"],
                            "explicitBounds": [10.0, 25.0],
                        }
                    ],
                },
            }
        )
    )
    r = rows[0]
    assert r["metric_type"] == "histogram"
    assert r["histogram_count"] == 7
    assert json.loads(r["histogram_bucket_counts"]) == [1, 4, 2]
    assert json.loads(r["histogram_explicit_bounds"]) == [10.0, 25.0]
    assert "DELTA" in r["histogram_aggregation_temporality_description"].upper()

    rows = flatten_otel_metrics(
        _metric_payload(
            {
                "name": "latency.exp",
                "exponentialHistogram": {
                    "aggregationTemporality": 2,
                    "dataPoints": [
                        {
                            "count": "5",
                            "sum": 12.0,
                            "scale": 2,
                            "zeroCount": "1",
                            "positive": {"offset": 3, "bucketCounts": ["2", "2"]},
                            "negative": {"offset": 0, "bucketCounts": ["0"]},
                        }
                    ],
                },
            }
        )
    )
    r = rows[0]
    assert r["metric_type"] == "exponential_histogram"
    assert r["exp_histogram_scale"] == 2
    assert r["exp_histogram_zero_count"] == 1
    assert json.loads(r["exp_histogram_positive_bucket_counts"]) == [2, 2]
    assert r["exp_histogram_positive_offset"] == 3

    rows = flatten_otel_metrics(
        _metric_payload(
            {
                "name": "gc.pause",
                "summary": {
                    "dataPoints": [
                        {
                            "count": "3",
                            "sum": 1.5,
                            "quantileValues": [
                                {"quantile": 0.5, "value": 0.4},
                                {"quantile": 0.99, "value": 0.9},
                            ],
                        }
                    ]
                },
            }
        )
    )
    r = rows[0]
    assert r["metric_type"] == "summary"
    assert r["summary_count"] == 3
    q = json.loads(r["summary_quantile_values"])
    assert q[1] == {"quantile": 0.99, "value": 0.9}


def test_traces_spans_events_links():
    payload = {
        "resourceSpans": [
            {
                "resource": RESOURCE,
                "scopeSpans": [
                    {
                        "scope": SCOPE,
                        "spans": [
                            {
                                "traceId": "aaaa",
                                "spanId": "bbbb",
                                "parentSpanId": "cccc",
                                "name": "GET /checkout",
                                "kind": 2,
                                "startTimeUnixNano": "1714557600000000000",
                                "endTimeUnixNano": "1714557601000000000",
                                "status": {"code": 2, "message": "boom"},
                                "attributes": [
                                    {"key": "http.status_code", "value": {"intValue": "500"}}
                                ],
                                "events": [
                                    {
                                        "timeUnixNano": "1714557600500000000",
                                        "name": "exception",
                                        "attributes": [
                                            {"key": "exception.type", "value": {"stringValue": "IOError"}}
                                        ],
                                    }
                                ],
                                "links": [{"traceId": "dddd", "spanId": "eeee"}],
                            }
                        ],
                    }
                ],
            }
        ]
    }
    rows = flatten_otel_traces(payload)
    assert len(rows) == 1
    r = rows[0]
    assert r["span_name"] == "GET /checkout"
    assert r["span_kind"] == 2 and r["span_kind_description"] == "SPAN_KIND_SERVER"
    assert r["span_status_code"] == 2
    assert r["span_status_description"] == "STATUS_CODE_ERROR"
    assert r["span_status_message"] == "boom"
    events = json.loads(r["span_events"])
    assert events[0]["name"] == "exception"
    links = json.loads(r["span_links"])
    assert links[0]["trace_id"] == "dddd"
    assert r["resource_service.name"] == "checkout"
    assert r["span_trace_id"] == "aaaa" and r["span_span_id"] == "bbbb"


def test_logs_severity_enrichment():
    payload = {
        "resourceLogs": [
            {
                "resource": RESOURCE,
                "scopeLogs": [
                    {
                        "scope": SCOPE,
                        "logRecords": [
                            {
                                "timeUnixNano": "1714557600000000000",
                                "severityNumber": 17,
                                "body": {"stringValue": "disk full"},
                                "attributes": [
                                    {"key": "disk", "value": {"stringValue": "/dev/sda"}}
                                ],
                            }
                        ],
                    }
                ],
            }
        ]
    }
    rows = flatten_otel_logs(payload)
    r = rows[0]
    assert r["body"] == "disk full"
    assert r["severity_number"] == 17
    assert "ERROR" in r["severity_text"].upper()
    assert r["disk"] == "/dev/sda"


# ---------------------------------------------------------------- vectorized


def test_nanos_batch_matches_scalar():
    """nanos_to_rfc3339_batch must agree with the scalar path exactly,
    including sub-ms truncation, junk, and sentinel values."""
    from parseable_tpu.otel.otel_utils import nanos_to_rfc3339, nanos_to_rfc3339_batch

    values = [
        None, "", 0, "0", "junk", 1714521600000000000,
        "1714521600123456789",  # ns precision -> truncates to us
        1714521600999999999, "-1000000000", 123,
    ]
    batch = nanos_to_rfc3339_batch(values)
    for v, got in zip(values, batch):
        assert got == nanos_to_rfc3339(v), (v, got, nanos_to_rfc3339(v))


def test_otel_logs_fast_decode_differential(parseable):
    """The vectorized ingest path (batch timestamps + arrow fast decode)
    must produce byte-identical staging rows to the per-record slow path
    over randomized OTel-logs payloads (VERDICT r2 #9)."""
    import random

    from parseable_tpu.event import format as F
    from parseable_tpu.event.json_format import JsonEvent
    from parseable_tpu.otel.logs import flatten_otel_logs

    rng = random.Random(17)

    def rand_payload():
        rls = []
        for g in range(rng.randint(1, 3)):
            recs = []
            for i in range(rng.randint(1, 40)):
                rec = {
                    "timeUnixNano": str(1714521600000000000 + rng.randint(0, 10**12)),
                    "body": {"stringValue": f"msg {rng.randint(0, 5)}"},
                }
                if rng.random() < 0.8:
                    rec["severityNumber"] = rng.randint(1, 24)
                if rng.random() < 0.5:
                    rec["observedTimeUnixNano"] = str(
                        1714521600000000000 + rng.randint(0, 10**12)
                    )
                if rng.random() < 0.5:
                    rec["attributes"] = [
                        {"key": "k1", "value": {"intValue": str(rng.randint(0, 9))}},
                        {"key": "k2", "value": {"doubleValue": rng.random()}},
                    ]
                if rng.random() < 0.3:
                    rec["traceId"] = f"{rng.getrandbits(64):032x}"
                recs.append(rec)
            rls.append(
                {
                    "resource": {
                        "attributes": [
                            {"key": "service.name", "value": {"stringValue": f"s{g}"}}
                        ]
                    },
                    "scopeLogs": [{"scope": {"name": "lg"}, "logRecords": recs}],
                }
            )
        return {"resourceLogs": rls}

    for trial in range(10):
        payload = rand_payload()
        rows = flatten_otel_logs(payload)
        stream = parseable.create_stream_if_not_exists(f"otldiff{trial}")
        fast_ev = JsonEvent(rows, f"otldiff{trial}").into_event(stream.metadata)
        orig = F.prepare_and_decode_fast
        F.prepare_and_decode_fast = lambda *a, **k: None  # force slow path
        try:
            import parseable_tpu.event.json_format as JF

            orig_jf = JF.prepare_and_decode_fast
            JF.prepare_and_decode_fast = lambda *a, **k: None
            slow_ev = JsonEvent(rows, f"otldiff{trial}").into_event(stream.metadata)
            JF.prepare_and_decode_fast = orig_jf
        finally:
            F.prepare_and_decode_fast = orig
        # p_timestamp is the wall-clock ingest stamp: excluded (differs
        # between the two runs by construction)
        fast_cols = sorted(n for n in fast_ev.rb.schema.names if n != "p_timestamp")
        slow_cols = sorted(n for n in slow_ev.rb.schema.names if n != "p_timestamp")
        fast_t = fast_ev.rb.select(fast_cols)
        slow_t = slow_ev.rb.select(slow_cols)
        assert fast_t.schema == slow_t.schema, f"trial {trial} schema diverged"
        assert fast_t == slow_t, f"trial {trial} rows diverged"


def test_nanos_batch_overflow_values():
    """fixed64 timeUnixNano values >= 2^63 must not crash the batch path."""
    from parseable_tpu.otel.otel_utils import nanos_to_rfc3339, nanos_to_rfc3339_batch

    vals = [2**63, str(2**63 + 5), 2**64 - 1, 1714521600000000000]
    batch = nanos_to_rfc3339_batch(vals)
    for v, got in zip(vals, batch):
        assert got == nanos_to_rfc3339(v)
