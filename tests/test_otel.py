"""OTel flattener semantics (reference: src/otel/{logs,metrics,traces}.rs —
SURVEY: "port semantics exactly"): all five metric kinds, span
events/links/enum enrichment, and end-to-end OTLP ingest -> query."""

import json

from parseable_tpu.otel.logs import flatten_otel_logs
from parseable_tpu.otel.metrics import flatten_otel_metrics
from parseable_tpu.otel.traces import flatten_otel_traces

RESOURCE = {
    "attributes": [
        {"key": "service.name", "value": {"stringValue": "checkout"}},
    ]
}
SCOPE = {"name": "meter", "version": "1.0"}


def _metric_payload(metric: dict) -> dict:
    return {
        "resourceMetrics": [
            {"resource": RESOURCE, "scopeMetrics": [{"scope": SCOPE, "metrics": [metric]}]}
        ]
    }


def test_gauge_and_sum():
    rows = flatten_otel_metrics(
        _metric_payload(
            {
                "name": "cpu.util",
                "unit": "%",
                "gauge": {
                    "dataPoints": [
                        {
                            "asDouble": 42.5,
                            "timeUnixNano": "1714557600000000000",
                            "attributes": [{"key": "core", "value": {"intValue": "3"}}],
                        }
                    ]
                },
            }
        )
    )
    assert len(rows) == 1
    r = rows[0]
    assert r["metric_type"] == "gauge"
    assert r["metric_name"] == "cpu.util"
    assert r["resource_service.name"] == "checkout"
    assert r["core"] == 3

    rows = flatten_otel_metrics(
        _metric_payload(
            {
                "name": "requests.total",
                "sum": {
                    "isMonotonic": True,
                    "aggregationTemporality": 2,
                    "dataPoints": [{"asInt": "128", "timeUnixNano": "1714557600000000000"}],
                },
            }
        )
    )
    r = rows[0]
    assert r["metric_type"] == "sum"
    assert r["sum_is_monotonic"] is True
    assert r["sum_aggregation_temporality"] == 2
    assert "CUMULATIVE" in r["sum_aggregation_temporality_description"].upper()


def test_histogram_exponential_and_summary():
    rows = flatten_otel_metrics(
        _metric_payload(
            {
                "name": "latency",
                "histogram": {
                    "aggregationTemporality": 1,
                    "dataPoints": [
                        {
                            "count": "7",
                            "sum": 99.5,
                            "min": 1.0,
                            "max": 50.0,
                            "bucketCounts": ["1", "4", "2"],
                            "explicitBounds": [10.0, 25.0],
                        }
                    ],
                },
            }
        )
    )
    r = rows[0]
    assert r["metric_type"] == "histogram"
    assert r["histogram_count"] == 7
    assert json.loads(r["histogram_bucket_counts"]) == [1, 4, 2]
    assert json.loads(r["histogram_explicit_bounds"]) == [10.0, 25.0]
    assert "DELTA" in r["histogram_aggregation_temporality_description"].upper()

    rows = flatten_otel_metrics(
        _metric_payload(
            {
                "name": "latency.exp",
                "exponentialHistogram": {
                    "aggregationTemporality": 2,
                    "dataPoints": [
                        {
                            "count": "5",
                            "sum": 12.0,
                            "scale": 2,
                            "zeroCount": "1",
                            "positive": {"offset": 3, "bucketCounts": ["2", "2"]},
                            "negative": {"offset": 0, "bucketCounts": ["0"]},
                        }
                    ],
                },
            }
        )
    )
    r = rows[0]
    assert r["metric_type"] == "exponential_histogram"
    assert r["exp_histogram_scale"] == 2
    assert r["exp_histogram_zero_count"] == 1
    assert json.loads(r["exp_histogram_positive_bucket_counts"]) == [2, 2]
    assert r["exp_histogram_positive_offset"] == 3

    rows = flatten_otel_metrics(
        _metric_payload(
            {
                "name": "gc.pause",
                "summary": {
                    "dataPoints": [
                        {
                            "count": "3",
                            "sum": 1.5,
                            "quantileValues": [
                                {"quantile": 0.5, "value": 0.4},
                                {"quantile": 0.99, "value": 0.9},
                            ],
                        }
                    ]
                },
            }
        )
    )
    r = rows[0]
    assert r["metric_type"] == "summary"
    assert r["summary_count"] == 3
    q = json.loads(r["summary_quantile_values"])
    assert q[1] == {"quantile": 0.99, "value": 0.9}


def test_traces_spans_events_links():
    payload = {
        "resourceSpans": [
            {
                "resource": RESOURCE,
                "scopeSpans": [
                    {
                        "scope": SCOPE,
                        "spans": [
                            {
                                "traceId": "aaaa",
                                "spanId": "bbbb",
                                "parentSpanId": "cccc",
                                "name": "GET /checkout",
                                "kind": 2,
                                "startTimeUnixNano": "1714557600000000000",
                                "endTimeUnixNano": "1714557601000000000",
                                "status": {"code": 2, "message": "boom"},
                                "attributes": [
                                    {"key": "http.status_code", "value": {"intValue": "500"}}
                                ],
                                "events": [
                                    {
                                        "timeUnixNano": "1714557600500000000",
                                        "name": "exception",
                                        "attributes": [
                                            {"key": "exception.type", "value": {"stringValue": "IOError"}}
                                        ],
                                    }
                                ],
                                "links": [{"traceId": "dddd", "spanId": "eeee"}],
                            }
                        ],
                    }
                ],
            }
        ]
    }
    rows = flatten_otel_traces(payload)
    assert len(rows) == 1
    r = rows[0]
    assert r["span_name"] == "GET /checkout"
    assert r["span_kind"] == 2 and r["span_kind_description"] == "SPAN_KIND_SERVER"
    assert r["span_status_code"] == 2
    assert r["span_status_description"] == "STATUS_CODE_ERROR"
    assert r["span_status_message"] == "boom"
    events = json.loads(r["span_events"])
    assert events[0]["name"] == "exception"
    links = json.loads(r["span_links"])
    assert links[0]["trace_id"] == "dddd"
    assert r["resource_service.name"] == "checkout"
    assert r["span_trace_id"] == "aaaa" and r["span_span_id"] == "bbbb"


def test_logs_severity_enrichment():
    payload = {
        "resourceLogs": [
            {
                "resource": RESOURCE,
                "scopeLogs": [
                    {
                        "scope": SCOPE,
                        "logRecords": [
                            {
                                "timeUnixNano": "1714557600000000000",
                                "severityNumber": 17,
                                "body": {"stringValue": "disk full"},
                                "attributes": [
                                    {"key": "disk", "value": {"stringValue": "/dev/sda"}}
                                ],
                            }
                        ],
                    }
                ],
            }
        ]
    }
    rows = flatten_otel_logs(payload)
    r = rows[0]
    assert r["body"] == "disk full"
    assert r["severity_number"] == 17
    assert "ERROR" in r["severity_text"].upper()
    assert r["disk"] == "/dev/sda"
