"""Native-path telemetry plane (observability tentpole).

The C++ fast path records per-shard parse spans, stitch time, and pool
queue-wait into a lock-free per-thread event ring (fastpath.cpp telem::)
drained over the ptpu_telem_* ABI by the SAME Python thread that
submitted the parse. The contracts under test:

- recording NEVER blocks or corrupts a parse: ring overflow drops events
  (counted in ptpu_telem_drops) and results stay exact;
- thread-local attribution: concurrent parse+drain on many threads never
  cross-contaminate (each thread drains exactly its own events);
- a traced sharded ingest stitches EXACTLY `shards` native child spans
  whose rows/bytes sum to the request totals, parented under the ingest
  span;
- pool introspection (size / queue depth / per-worker busy ns) and the
  scrape-time gauge refresh;
- the native_rows_conserved audit invariant balances on real ingest and
  trips on a fabricated imbalance;
- single-owner drain handles never leak (telem_live == 0 at rest —
  also enforced globally by conftest's session-finish gate).
"""

from __future__ import annotations

import gc
import json
import threading

import pytest

from parseable_tpu import native
from parseable_tpu.config import Options, StorageOptions
from parseable_tpu.core import Parseable
from parseable_tpu.event.format import LogSource
from parseable_tpu.server.ingest_utils import flatten_and_push_logs
from parseable_tpu.utils import telemetry
from parseable_tpu.utils.metrics import REGISTRY

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native fastpath unavailable"
)

DEPTH = Options().event_flatten_level - 1


def mk(tmp_path) -> Parseable:
    opts = Options()
    opts.local_staging_path = tmp_path / "staging"
    return Parseable(
        opts, StorageOptions(backend="local-store", root=tmp_path / "data")
    )


@pytest.fixture(autouse=True)
def _clean_ring():
    """Every test starts and ends with an empty ring on this thread."""
    native.telem_sync()
    native.telem_drain()
    yield
    native.telem_drain()


# ------------------------------------------------------------ ring mechanics


def test_ring_overflow_drops_counted_never_blocks():
    """More undrained parses than the ring holds: the surplus is dropped
    and counted, every parse still returns exact results, and the drained
    remainder + drop delta accounts for every event."""
    body = json.dumps([{"a": i, "b": "x" * 8} for i in range(25)]).encode()
    calls = 300  # ring capacity is 256; anything >256 must overflow
    drops_before = native.telem_drops()
    for _ in range(calls):
        r = native.flatten_columnar(body, DEPTH)
        assert r is not None and r[2] == 25, "overflow corrupted a parse"
    drained = native.telem_drain()
    dropped = native.telem_drops() - drops_before
    assert dropped > 0, "300 undrained events never overflowed the ring"
    assert dropped + len(drained) == calls
    assert all(e[5] == 25 for e in drained), drained
    gc.collect()
    assert native.telem_live() == 0


def test_event_fields_unsharded():
    body = json.dumps([{"a": i} for i in range(10)]).encode()
    r = native.flatten_columnar(body, DEPTH)
    assert r is not None
    evs = native.telem_drain()
    assert len(evs) == 1
    kind, shard, lane, rc, nbytes, rows, start_ns, dur_ns, qwait_ns = evs[0]
    assert kind == native.TELEM_EV_PARSE
    assert shard == 0 and qwait_ns == 0  # inline parse: no pool wait
    assert native.TELEM_LANES[lane] == "json"
    assert native.TELEM_CAUSES[rc] == "ok"
    assert nbytes == len(body) and rows == 10
    assert start_ns > 0 and dur_ns > 0


def test_decline_events_carry_cause():
    """A payload the columnar builders decline still records its parse
    attempt, with a non-ok cause code — the waterfall sees declines."""
    body = json.dumps([{"a": [1, 2, 3]}]).encode()  # arrays: columnar declines
    assert native.flatten_columnar(body, DEPTH) is None
    evs = native.telem_drain()
    assert evs, "declined parse recorded no event"
    assert any(native.TELEM_CAUSES.get(e[3]) != "ok" for e in evs), evs


def test_sharded_events_sum_exactly():
    """Per-shard byte/row accounting: shard slices cover the payload with
    no gap or overlap, rows sum to the total, and the stitch event rides
    along; shard>0 jobs carry a real pool queue-wait."""
    body = json.dumps([{"a": i, "s": "y" * 30} for i in range(2000)]).encode()
    r = native.flatten_columnar(body, DEPTH, shards=4)
    assert r is not None and r[2] == 2000
    evs = native.telem_drain()
    parse = [e for e in evs if e[0] == native.TELEM_EV_PARSE]
    stitch = [e for e in evs if e[0] == native.TELEM_EV_STITCH]
    assert len(parse) == 4 and len(stitch) == 1
    assert sorted(e[1] for e in parse) == [0, 1, 2, 3]
    assert sum(e[5] for e in parse) == 2000
    assert sum(e[4] for e in parse) == len(body)
    assert stitch[0][5] == 2000
    # only the non-inline shards wait on the pool queue
    assert parse[0][8] == 0 or any(e[8] > 0 for e in parse[1:])


def test_telem_disabled_records_nothing(monkeypatch):
    monkeypatch.setenv("P_NATIVE_TELEM", "0")
    assert native.telem_sync() is False
    body = json.dumps([{"a": 1}]).encode()
    r = native.flatten_columnar(body, DEPTH, shards=2)
    assert r is not None
    assert native.telem_drain() == []
    monkeypatch.delenv("P_NATIVE_TELEM")
    assert native.telem_sync() is True  # knob re-syncs without a reload


# ------------------------------------------------------- drain-vs-parse race


def test_drain_vs_parse_thread_isolation():
    """Concurrent threads parse (sharded and not) and drain in a tight
    loop: every thread must drain exactly its own events — row totals per
    drain match that thread's payload, with zero cross-thread bleed —
    while pool workers race CallBuf publication underneath."""
    errors: list[BaseException] = []

    def worker(idx: int) -> None:
        nrows = 40 + idx  # per-thread row count: contamination breaks sums
        body = json.dumps(
            [{"a": i, "w": idx, "pad": "z" * 20} for i in range(nrows)]
        ).encode()
        try:
            for it in range(40):
                shards = 1 + (idx + it) % 3
                r = native.flatten_columnar(body, DEPTH, shards=shards)
                assert r is not None and r[2] == nrows
                evs = native.telem_drain()
                parse = [e for e in evs if e[0] == native.TELEM_EV_PARSE]
                assert sum(e[5] for e in parse) == nrows, (
                    f"thread {idx} drained foreign events: {evs}"
                )
                assert sum(e[4] for e in parse) == len(body)
        except BaseException as e:  # noqa: BLE001 — surfaced to the test
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    # the main thread submitted nothing: its ring must be empty
    assert native.telem_drain() == []
    gc.collect()
    assert native.telem_live() == 0 and native.columnar_live() == 0


# --------------------------------------------------------- stitched waterfall


def test_stitched_trace_exact_shard_spans(tmp_path, monkeypatch):
    """A traced sharded ingest must contain exactly `shards` native.parse
    child spans whose rows/bytes sum to the request totals, plus one
    native.stitch — all parented under the request's ingest span."""
    monkeypatch.setenv("P_INGEST_PARSE_SHARDS", "4")
    monkeypatch.setenv("P_INGEST_SHARD_MIN_BYTES", "0")
    p = mk(tmp_path)
    try:
        p.create_stream_if_not_exists("s")
        body = json.dumps(
            [{"host": f"h{i % 5}", "v": float(i)} for i in range(1000)]
        ).encode()
        telemetry.clear_recent_spans()
        with telemetry.trace_context() as trace_id:
            count = flatten_and_push_logs(
                p, "s", None, LogSource.JSON, {}, raw_body=body
            )
        assert count == 1000
        spans = telemetry.recent_spans(trace_id)
        parse = [s for s in spans if s["name"] == "native.parse"]
        stitch = [s for s in spans if s["name"] == "native.stitch"]
        ingest = [s for s in spans if s["name"] == "ingest"]
        assert len(parse) == 4, [s["name"] for s in spans]
        assert sum(s["rows"] for s in parse) == 1000
        assert sum(s["bytes"] for s in parse) == len(body)
        assert len(stitch) == 1 and stitch[0]["rows"] == 1000
        assert len(ingest) == 1
        for s in parse + stitch:
            assert s["parent_span_id"] == ingest[0]["span_id"]
            assert s["duration_ms"] > 0
    finally:
        p.shutdown()
        telemetry.clear_recent_spans()


def test_stage_histograms_and_imbalance_gauge(tmp_path, monkeypatch):
    """One native ingest populates the per-lane stage waterfall histograms
    (parse + schema-commit + stage-ipc) and a sharded one refreshes the
    shard-imbalance gauge."""

    def stage_count(stage: str, lane: str) -> float:
        return (
            REGISTRY.get_sample_value(
                "parseable_ingest_stage_seconds_count",
                {"stage": stage, "lane": lane},
            )
            or 0.0
        )

    before = {
        ("parse", "json"): stage_count("parse", "json"),
        ("stitch", "json"): stage_count("stitch", "json"),
        ("schema-commit", "json"): stage_count("schema-commit", "json"),
        ("stage-ipc", "json"): stage_count("stage-ipc", "json"),
    }
    monkeypatch.setenv("P_INGEST_PARSE_SHARDS", "2")
    monkeypatch.setenv("P_INGEST_SHARD_MIN_BYTES", "0")
    p = mk(tmp_path)
    try:
        p.create_stream_if_not_exists("s")
        body = json.dumps([{"a": i} for i in range(500)]).encode()
        count = flatten_and_push_logs(
            p, "s", None, LogSource.JSON, {}, raw_body=body
        )
        assert count == 500
        assert stage_count("parse", "json") == before[("parse", "json")] + 2
        assert stage_count("stitch", "json") == before[("stitch", "json")] + 1
        assert (
            stage_count("schema-commit", "json")
            == before[("schema-commit", "json")] + 1
        )
        assert stage_count("stage-ipc", "json") == before[("stage-ipc", "json")] + 1
        imb = REGISTRY.get_sample_value("parseable_ingest_shard_imbalance")
        assert imb is not None and imb >= 1.0
    finally:
        p.shutdown()


# ------------------------------------------------------------- pool gauges


def test_pool_introspection_and_busy_monotonic():
    body = json.dumps([{"a": i, "pad": "q" * 20} for i in range(3000)]).encode()
    r = native.flatten_columnar(body, DEPTH, shards=4)
    assert r is not None
    native.telem_drain()
    size = native.parse_pool_size()
    assert size >= 1, "sharded parse left no live pool workers"
    assert native.pool_queue_depth() >= 0
    busy1 = sum(native.pool_busy_ns(w) for w in range(size))
    r = native.flatten_columnar(body, DEPTH, shards=4)
    assert r is not None
    native.telem_drain()
    busy2 = sum(native.pool_busy_ns(w) for w in range(size))
    assert busy2 >= busy1, "busy counters must be monotonic"
    # out-of-range worker slots answer 0, never fault
    assert native.pool_busy_ns(10_000) == 0 and native.pool_busy_ns(-1) == 0


def test_metrics_refresh_sets_pool_gauges(tmp_path):
    from parseable_tpu.server import app as server_app

    body = json.dumps([{"a": i} for i in range(2000)]).encode()
    assert native.flatten_columnar(body, DEPTH, shards=2) is not None
    native.telem_drain()
    server_app._refresh_native_pool_gauges()
    size = REGISTRY.get_sample_value("parseable_native_pool_size")
    depth = REGISTRY.get_sample_value("parseable_native_pool_queue_depth")
    drops = REGISTRY.get_sample_value("parseable_native_telem_dropped_events")
    assert size is not None and size >= 1
    assert depth is not None and depth >= 0
    assert drops is not None and drops >= 0
    # second refresh computes per-worker busy ratios from the deltas
    server_app._refresh_native_pool_gauges()
    ratio = REGISTRY.get_sample_value(
        "parseable_native_pool_busy_ratio", {"worker": "0"}
    )
    assert ratio is not None and 0.0 <= ratio <= 1.0


# ---------------------------------------------------------------- audit tie-in


def test_native_rows_conserved_balances_and_trips(tmp_path):
    from parseable_tpu import audit

    p = mk(tmp_path)
    try:
        p.create_stream_if_not_exists("s")
        p.audit.ensure_stream(p, "s")
        body = json.dumps([{"a": i} for i in range(20)]).encode()
        count = flatten_and_push_logs(
            p, "s", None, LogSource.JSON, {}, raw_body=body
        )
        assert count == 20
        p.audit.record_acked("s", count)
        rep = audit.local_report(p, quiesce=True)
        assert rep["violations"] == [], rep["violations"]
        entry = rep["streams"]["s"]
        assert entry["native_parsed"] == 20
        assert entry["native_staged"] == 20
        assert entry["native_declined"] == 0
        # fabricate rows that parsed natively but neither staged nor
        # declined — the invariant must trip at quiesce
        p.audit.record_native("s", parsed=5)
        rep = audit.local_report(p, quiesce=True)
        broken = [
            v for v in rep["violations"] if v["invariant"] == "native_rows_conserved"
        ]
        assert broken, rep["violations"]
    finally:
        p.shutdown()


def test_native_decline_cascade_balances(tmp_path):
    """A columnar parse whose normalization declines pushes the rows down
    a tier; the books must balance (parsed == staged + declined) even
    though two tiers each counted their own parse."""
    from parseable_tpu import audit

    p = mk(tmp_path)
    try:
        p.create_stream_if_not_exists("s")
        p.audit.ensure_stream(p, "s")
        # int-typed column then string-typed same column: the second batch
        # parses columnar but the stored-schema normalization declines it
        for payload in ([{"a": 1}], [{"a": "not an int"}]):
            body = json.dumps(payload).encode()
            try:
                flatten_and_push_logs(p, "s", None, LogSource.JSON, {}, raw_body=body)
            except Exception:  # noqa: BLE001 — only the books matter here
                pass  # the authoritative Python path may reject the batch
        counters = p.audit.native_counters().get("s")
        assert counters is not None
        parsed, staged, declined = counters
        assert parsed == staged + declined, counters
        rep = audit.local_report(p, quiesce=False)
        assert [
            v for v in rep["violations"] if v["invariant"] == "native_rows_conserved"
        ] == []
    finally:
        p.shutdown()
