"""Benchmark: time-bucketed GROUP BY aggregation, TPU engine vs CPU baseline.

Reproduces BASELINE.md config 2 (time-bucketed GROUP BY (p_timestamp, status)
COUNT over a flog-style JSON log stream) through the full stack: staging ->
parquet -> catalog -> manifest-pruned scan -> engine.

Prints ONE json line:
    {"metric": ..., "value": rows/sec on TPU, "unit": "rows/s",
     "vs_baseline": speedup over the CPU pyarrow engine}

Env knobs: BENCH_ROWS (default 2_000_000), BENCH_REPEATS (default 3).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time
from datetime import UTC, datetime, timedelta

import numpy as np
import pyarrow as pa


def build_dataset(p, stream_name: str, total_rows: int) -> None:
    """Synthesize a flog-like access-log stream through the real pipeline."""
    from parseable_tpu import DEFAULT_TIMESTAMP_KEY
    from parseable_tpu.event import Event

    rng = np.random.default_rng(42)
    stream = p.create_stream_if_not_exists(stream_name)
    base = datetime(2024, 5, 1, 0, 0, tzinfo=UTC)
    batch_rows = 1_000_000  # one "minute" of a high-throughput stream
    statuses = np.array([200, 200, 200, 200, 301, 404, 500, 503])
    hosts = np.array([f"10.0.{i}.{j}" for i in range(4) for j in range(8)])
    methods = np.array(["GET", "GET", "GET", "POST", "PUT", "DELETE"])
    paths = np.array([f"/api/v1/resource{i}" for i in range(64)])
    written = 0
    minute = 0
    while written < total_rows:
        n = min(batch_rows, total_rows - written)
        ts_offsets = np.sort(rng.integers(0, 60_000, n))
        ts = [base + timedelta(minutes=minute, milliseconds=int(o)) for o in ts_offsets]
        tbl = pa.table(
            {
                DEFAULT_TIMESTAMP_KEY: pa.array(
                    [t.replace(tzinfo=None) for t in ts], pa.timestamp("ms")
                ),
                "host": pa.array(hosts[rng.integers(0, len(hosts), n)]),
                "method": pa.array(methods[rng.integers(0, len(methods), n)]),
                "path": pa.array(paths[rng.integers(0, len(paths), n)]),
                "status": pa.array(statuses[rng.integers(0, len(statuses), n)].astype(np.float64)),
                "bytes": pa.array(rng.integers(100, 50_000, n).astype(np.float64)),
                "latency_ms": pa.array((rng.random(n) * 500).astype(np.float64)),
            }
        ).combine_chunks()
        for batch in tbl.to_batches():
            ev = Event(
                stream_name=stream_name,
                rb=batch,
                origin_size=batch.num_rows * 120,
                is_first_event=written == 0,
                parsed_timestamp=base + timedelta(minutes=minute),
            )
            ev.process(stream, commit_schema=p.commit_schema)
        written += n
        minute += 1
    p.local_sync(shutdown=True)
    p.sync_all_streams()


QUERY = (
    "SELECT date_bin(interval '1 minute', p_timestamp) AS t, status, count(*) AS c, "
    "sum(bytes) AS b, avg(latency_ms) AS l FROM {stream} GROUP BY t, status"
)


def run_engine(p, stream: str, engine: str, repeats: int) -> tuple[float, int, list]:
    from parseable_tpu.query.session import QuerySession

    sess = QuerySession(p, engine=engine)
    best = float("inf")
    rows_scanned = 0
    result_rows = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = sess.query(QUERY.format(stream=stream))
        dt = time.perf_counter() - t0
        best = min(best, dt)
        rows_scanned = res.stats["rows_scanned"]
        result_rows = sorted(
            (str(r.get("t")), r.get("status"), r.get("c")) for r in res.to_json_rows()
        )
    return best, rows_scanned, result_rows


def main() -> None:
    total_rows = int(os.environ.get("BENCH_ROWS", "32000000"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))

    workdir = tempfile.mkdtemp(prefix="ptpu-bench-")
    try:
        from parseable_tpu.config import Options, StorageOptions
        from parseable_tpu.core import Parseable

        opts = Options()
        opts.local_staging_path = __import__("pathlib").Path(workdir) / "staging"
        storage = StorageOptions(backend="local-store", root=__import__("pathlib").Path(workdir) / "data")
        p = Parseable(opts, storage)

        t0 = time.perf_counter()
        build_dataset(p, "bench", total_rows)
        print(f"# dataset: {total_rows} rows built+cataloged in {time.perf_counter()-t0:.1f}s", file=sys.stderr)

        import jax

        print(f"# devices: {jax.devices()}", file=sys.stderr)

        # warm both engines (first TPU call pays XLA compile)
        run_engine(p, "bench", "cpu", 1)
        run_engine(p, "bench", "tpu", 1)

        cpu_t, rows, cpu_rows = run_engine(p, "bench", "cpu", repeats)
        tpu_t, _, tpu_rows = run_engine(p, "bench", "tpu", repeats)

        if cpu_rows != tpu_rows:
            print("# WARNING: engine results differ!", file=sys.stderr)
            print(f"#   cpu: {cpu_rows[:3]}... tpu: {tpu_rows[:3]}...", file=sys.stderr)

        tpu_rps = rows / tpu_t
        cpu_rps = rows / cpu_t
        print(
            f"# cpu: {cpu_t:.3f}s ({cpu_rps:,.0f} rows/s)  tpu: {tpu_t:.3f}s ({tpu_rps:,.0f} rows/s)",
            file=sys.stderr,
        )
        print(
            json.dumps(
                {
                    "metric": "groupby_scan_rows_per_sec_tpu",
                    "value": round(tpu_rps, 1),
                    "unit": "rows/s",
                    "vs_baseline": round(cpu_t / tpu_t, 3),
                }
            )
        )
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    main()
