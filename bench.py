"""Benchmarks: the BASELINE.md configs, TPU engine vs CPU baseline.

Runs through the full stack (staging -> parquet -> catalog -> manifest-
pruned scan -> engine) over one synthesized flog/OTel-style stream:

- config 2: time-bucketed GROUP BY (p_timestamp, status) aggregation;
- config 3: LIKE substring filter on the message column (the dictionary-
  LUT predicate path's showcase);
- config 4 (north star): top-K + multi-column GROUP BY, reported COLD
  (first scan: parquet read + encode + transfer overlapped via the
  prefetcher) and WARM (device hot set resident);
- config 5: the distributed psum-tree path, validated on a virtual
  8-device CPU mesh in a subprocess (the bench host has one real chip).

Prints one JSON line per config; the LAST line is the headline north-star
metric the driver records. Env knobs: BENCH_ROWS (default 32_000_000),
BENCH_REPEATS (default 3).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from datetime import datetime, timedelta

try:
    from datetime import UTC
except ImportError:  # py3.10: parseable_tpu installs the datetime.UTC shim
    from datetime import timezone as _tz

    UTC = _tz.utc

import numpy as np
import pyarrow as pa


def build_dataset(
    p,
    stream_name: str,
    total_rows: int,
    profile: str = "default",
    sync_every: int | None = None,
) -> None:
    """Synthesize an access-log stream through the real pipeline.

    Profiles (VERDICT r2 "de-rig the benchmark"):
    - "default": flog-like, low-cardinality columns (32 hosts, 64 paths,
      ~27 message templates) — blocks dictionary-encode tightly, the
      dictionary-LUT design's best case;
    - "highcard": ~10k hosts, ~100k paths, and messages templated with
      random request ids so per-block message uniques ≈ 50k — the case
      where host-side dictionary encode and the group-space explosion are
      the real costs.
    - "highentropy": low-compressibility numerics (full-range uniform
      bytes/latency, random per-row message ids) so parquet compression
      buys ~nothing and disk size approaches logical size — the profile
      the tiering story must survive (memory-pressure runs cap
      P_TPU_HOT_BYTES below the working set; see bench_memory_pressure).
      Group keys stay moderate-cardinality so the device group space is
      dense while the payload bytes stay incompressible.
    """
    from parseable_tpu import DEFAULT_TIMESTAMP_KEY
    from parseable_tpu.event import Event

    rng = np.random.default_rng(42)
    stream = p.create_stream_if_not_exists(stream_name)
    base = datetime(2024, 5, 1, 0, 0, tzinfo=UTC)
    batch_rows = 1_000_000  # one "minute" of a high-throughput stream
    statuses = np.array([200, 200, 200, 200, 301, 404, 500, 503])
    methods = np.array(["GET", "GET", "GET", "POST", "PUT", "DELETE"])
    if profile == "highcard":
        hosts = np.array(
            [f"10.{i}.{j}.{k}" for i in range(10) for j in range(32) for k in range(32)]
        )  # 10,240 hosts
        paths = np.array(
            [f"/api/v1/tenant{t}/resource{r}" for t in range(400) for r in range(256)]
        )  # 102,400 paths
        messages = None  # synthesized per batch with unique request ids
    elif profile == "highentropy":
        # moderate-cardinality group keys (dense device group space), but
        # per-row-unique messages: every batch's message column is ~pure
        # entropy, so parquet compression buys nothing and disk size
        # approaches logical size (the tiering-under-pressure profile)
        hosts = np.array([f"10.0.{i}.{j}" for i in range(8) for j in range(16)])
        paths = np.array([f"/api/v1/resource{i}" for i in range(128)])
        messages = None  # synthesized per batch with unique request ids
    else:
        hosts = np.array([f"10.0.{i}.{j}" for i in range(4) for j in range(8)])
        paths = np.array([f"/api/v1/resource{i}" for i in range(64)])
        # OTel-ish message bodies: low-cardinality template set so blocks
        # dictionary-encode (config 3 exercises the LUT regex path)
        messages = np.array(
            [f"request completed in {d}ms" for d in range(0, 400, 25)]
            + [f"error: upstream timeout after {d}ms" for d in range(0, 400, 50)]
            + [f"slow query warning threshold {d}" for d in range(0, 200, 25)]
            + ["connection reset by peer", "error: permission denied", "cache miss"]
        )
    written = 0
    minute = 0
    while written < total_rows:
        n = min(batch_rows, total_rows - written)
        ts_offsets = np.sort(rng.integers(0, 60_000, n))
        ts = [base + timedelta(minutes=minute, milliseconds=int(o)) for o in ts_offsets]
        if messages is None:
            # ~50k unique messages per 1M-row batch: templates carry a
            # request id drawn from a batch-fresh window
            req_ids = rng.integers(minute * 50_000, minute * 50_000 + 50_000, n)
            tmpl = rng.integers(0, 4, n)
            msg_arr = np.empty(n, dtype=object)
            for t_i, fmt in enumerate(
                (
                    "request %d completed in 34ms",
                    "error: upstream timeout for request %d",
                    "slow query warning for request %d",
                    "request %d cache miss",
                )
            ):
                sel_rows = tmpl == t_i
                msg_arr[sel_rows] = [fmt % r for r in req_ids[sel_rows]]
            batch_messages = pa.array(msg_arr.tolist())
        else:
            batch_messages = pa.array(messages[rng.integers(0, len(messages), n)])
        tbl = pa.table(
            {
                DEFAULT_TIMESTAMP_KEY: pa.array(
                    [t.replace(tzinfo=None) for t in ts], pa.timestamp("ms")
                ),
                "host": pa.array(hosts[rng.integers(0, len(hosts), n)]),
                "method": pa.array(methods[rng.integers(0, len(methods), n)]),
                "path": pa.array(paths[rng.integers(0, len(paths), n)]),
                "message": batch_messages,
                "status": pa.array(statuses[rng.integers(0, len(statuses), n)].astype(np.float64)),
                # highentropy: full-mantissa uniform floats defeat both
                # parquet byte-stream compression and dictionary encoding
                "bytes": pa.array(
                    (rng.random(n) * 50_000).astype(np.float64)
                    if profile == "highentropy"
                    else rng.integers(100, 50_000, n).astype(np.float64)
                ),
                "latency_ms": pa.array((rng.random(n) * 500).astype(np.float64)),
            }
        ).combine_chunks()
        for batch in tbl.to_batches():
            ev = Event(
                stream_name=stream_name,
                rb=batch,
                origin_size=batch.num_rows * 150,
                is_first_event=written == 0,
                parsed_timestamp=base + timedelta(minutes=minute),
            )
            ev.process(stream, commit_schema=p.commit_schema)
        written += n
        minute += 1
        if sync_every and minute % sync_every == 0:
            # large builds: convert + upload as we go so staging arrows
            # (uncompressed, ~3x the parquet bytes) never accumulate —
            # the backdated minute buckets all count as past, so a plain
            # local_sync finishes and compacts everything written so far
            p.local_sync(shutdown=True)
            p.sync_all_streams()
    p.local_sync(shutdown=True)
    p.sync_all_streams()


CONFIGS = {
    # BASELINE config 2: time-bucketed GROUP BY aggregation
    "groupby": (
        "SELECT date_bin(interval '1 minute', p_timestamp) AS t, status, count(*) AS c, "
        "sum(bytes) AS b, avg(latency_ms) AS l FROM {stream} GROUP BY t, status"
    ),
    # BASELINE config 3: substring/LIKE filter (dictionary-LUT predicates)
    "regex_filter": (
        "SELECT status, count(*) AS c, avg(latency_ms) AS l FROM {stream} "
        "WHERE message LIKE '%error%' GROUP BY status"
    ),
    # BASELINE config 4: top-K + multi-column GROUP BY (north star)
    "topk_multicol": (
        "SELECT path, host, count(*) AS c, sum(bytes) AS s FROM {stream} "
        "GROUP BY path, host ORDER BY s DESC LIMIT 10"
    ),
}


def run_query(p, stream: str, engine: str, sql: str) -> tuple[float, int, list, dict]:
    from parseable_tpu.query.session import QuerySession

    sess = QuerySession(p, engine=engine)
    t0 = time.perf_counter()
    res = sess.query(sql.format(stream=stream))
    dt = time.perf_counter() - t0
    rows = sorted(
        (tuple(r.values()) for r in res.to_json_rows()),
        key=lambda t: tuple(str(v) for v in t),
    )
    return dt, res.stats["rows_scanned"], rows, res.stats


def percentile(times: list[float], q: float) -> float:
    """Nearest-rank percentile over the measured repeats."""
    if not times:
        return 0.0
    xs = sorted(times)
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


def rows_match(a: list, b: list) -> bool:
    """Exact on keys/counts; 1e-4 relative on floats (device sums are f32
    per block; BENCH parity tolerance matches the test suite's)."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if abs(va - vb) > 1e-4 * max(1.0, abs(va)):
                    return False
            elif va != vb:
                return False
    return True


def timed_runs(p, stream, engine, sql, repeats) -> dict:
    """Run `repeats` times and report latency PERCENTILES, not a single
    shot or best-of (VERDICT missing #5: p50/p95 per config — a best-of
    hides tail variance the latency north star is supposed to capture)."""
    times: list[float] = []
    rows_scanned, result, stats = 0, [], {}
    for _ in range(max(1, repeats)):
        dt, scanned, rows, st = run_query(p, stream, engine, sql)
        times.append(dt)
        rows_scanned = max(rows_scanned, scanned)
        result, stats = rows, st
    return {
        "times": times,
        "p50": percentile(times, 0.50),
        "p95": percentile(times, 0.95),
        "best": min(times),
        "rows_scanned": rows_scanned,
        "rows": result,
        "stats": stats,
    }


def clear_hot_state() -> None:
    """Force the next TPU run cold: drop device-resident blocks."""
    from parseable_tpu.ops.hotset import get_hotset

    hs = get_hotset()
    try:
        hs.clear()
    except AttributeError:
        for key in list(getattr(hs, "entries", {})):
            hs.evict(key)


def emit(name: str, tpu_rps: float, speedup: float, extra: dict | None = None) -> None:
    line = {
        "metric": name,
        "value": round(tpu_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(speedup, 3),
    }
    if extra:
        line.update(extra)
    print(json.dumps(line), flush=True)
    # every emission also lands in the machine-readable artifact
    # (BENCH_JSON_OUT, one JSON object per line, appended) so the perf
    # trajectory — gb_per_sec, rows_per_sec_per_core, latency percentiles —
    # is diffable across rounds without scraping stdout
    out = os.environ.get("BENCH_JSON_OUT", "/tmp/bench.json")
    if out:
        try:
            with open(out, "a", encoding="utf-8") as f:
                f.write(json.dumps(line) + "\n")
        except OSError:
            pass


def bench_distributed_subprocess(total_rows: int) -> None:
    """Config 5: the shard_map psum path on a virtual 8-device CPU mesh.

    Runs in a subprocess because this process's JAX is bound to the real
    chip; the virtual mesh validates the distributed path end-to-end and
    reports its (CPU-device) throughput for the record.

    Measurement protocol (VERDICT r4 #9 — the raw number swung 3x across
    rounds purely with host size/load): the emission is load-qualified.
    It always carries `cpus` (the affinity-mask size the 8 virtual
    devices actually share) and `rows_per_sec_per_cpu` (the cross-round
    comparable figure), and is marked `degraded: true` when load1/cpus
    exceeds 0.25 at the start of the run — a degraded number is recorded
    for continuity but must not be read as a regression."""
    script = r"""
import os, time, json
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS","") + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, pyarrow as pa
from datetime import datetime, timedelta
from parseable_tpu import DEFAULT_TIMESTAMP_KEY
from parseable_tpu.query.sql import parse_sql
from parseable_tpu.query.planner import plan as build_plan
from parseable_tpu.query import executor_tpu as ET

n = %d
rng = np.random.default_rng(0)
base = datetime(2024, 5, 1)
ts = [base + timedelta(seconds=int(i)) for i in rng.integers(0, 3600, n)]
t = pa.table({
    DEFAULT_TIMESTAMP_KEY: pa.array(ts, pa.timestamp("ms")),
    "status": pa.array(rng.choice(["200","404","500"], n).tolist()),
    "bytes": pa.array(rng.random(n) * 1000),
})
sql = "SELECT status, count(*) c, sum(bytes) s FROM t GROUP BY status"
lp = build_plan(parse_sql(sql))
ex = ET.TpuQueryExecutor(lp)
assert ex.mesh is not None and ex.mesh.size == 8
ex.execute(iter([t]))  # warm/compile
# best-of-3: the r02->r03 "34%% regression" (6.9M->4.5M rows/s) was pure
# end-of-round machine load — r02/r03/r04 code measured back-to-back on
# an idle box all sit at ~11-13M rows/s (bisected round 4); a single
# timed run is hostage to whatever the driver is doing
best = 0.0
for _ in range(3):
    t0 = time.perf_counter()
    out = ex.execute(iter([t]))
    dt = time.perf_counter() - t0
    best = max(best, n / dt)
assert ET.MESH_PROGRAMS_BUILT > 0, "mesh program missing"
assert sum(r["c"] for r in out.to_pylist()) == n
load1 = os.getloadavg()[0]
cpus = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") else (os.cpu_count() or 1)
print(json.dumps({"ok": True, "rows_per_sec": best, "devices": 8, "load1": load1, "cpus": cpus}))
""" % min(total_rows, 2_000_000)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        last = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else "{}"
        data = json.loads(last)
        print(
            f"# distributed (virtual 8-dev mesh): ok={data.get('ok')} "
            f"{data.get('rows_per_sec', 0):,.0f} rows/s",
            file=sys.stderr,
        )
        rps = float(data.get("rows_per_sec", 0.0))
        cpus = int(data.get("cpus") or 1)
        load1 = float(data.get("load1") or 0.0)
        emit(
            "distributed_mesh_groupby_rows_per_sec",
            rps,
            1.0,
            {
                "devices": 8,
                "note": "virtual CPU mesh validation (1 real chip on host)",
                "best_of": 3,
                "host_load1": load1,
                "cpus": cpus,
                "rows_per_sec_per_cpu": round(rps / cpus, 1),
                "degraded": load1 / cpus > 0.25,
            },
        )
    except Exception as e:
        print(f"# distributed bench failed: {e}", file=sys.stderr)
        if "out" in dir():
            print(out.stderr[-2000:], file=sys.stderr)


def bench_config1(p, with_tpu: bool) -> None:
    """BASELINE config 1: `SELECT count(*) FROM demo WHERE host='...'` over
    the demo-data stream (reference: resources/ingest_demo_data.sh feeding
    handlers/http/query.rs:221-271's counts path).

    Ingests the packaged demo workload through the real JSON event path
    (server/extras.py generate_demo_events — the in-process port of the
    reference's demo script), then emits one line per engine for the
    filtered count, plus the manifest-count fast path for the unfiltered
    count validated against a full scan."""
    from parseable_tpu.event.json_format import JsonEvent
    from parseable_tpu.server.extras import generate_demo_events

    n = int(os.environ.get("BENCH_DEMO_ROWS", "1000000"))
    chunk = 50_000
    stream = p.create_stream_if_not_exists("demodata")
    t0 = time.perf_counter()
    done = 0
    while done < n:
        k = min(chunk, n - done)
        ev = JsonEvent(generate_demo_events(k, seed=done), "demodata").into_event(stream.metadata)
        ev.process(stream, commit_schema=p.commit_schema)
        done += k
    p.local_sync(shutdown=True)
    p.sync_all_streams()
    print(f"# demo stream: {n} rows ingested in {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    filtered = "SELECT count(*) AS c FROM demodata WHERE host='192.168.1.7'"
    engines = ["cpu"] + (["tpu"] if with_tpu else [])
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    for engine in engines:
        r = timed_runs(p, "demodata", engine, filtered, repeats)
        p50, scanned, rows = r["p50"], r["rows_scanned"], r["rows"]
        print(
            f"# config1 [{engine}]: count(*) WHERE host=... -> {rows[0][0]} in "
            f"p50 {p50:.3f}s p95 {r['p95']:.3f}s ({scanned/p50:,.0f} rows/s scanned)",
            file=sys.stderr,
        )
        emit(
            f"config1_filtered_count_rows_per_sec_{engine}",
            scanned / p50,
            1.0,
            {
                "latency_p50_s": round(p50, 4),
                "latency_p95_s": round(r["p95"], 4),
                "repeats": repeats,
                "matched": rows[0][0],
            },
        )

    # unfiltered count: manifest fast path vs a forced full scan (the
    # predicate defeats count_star_only without changing the answer)
    from parseable_tpu.query.session import QuerySession

    sess = QuerySession(p, engine="cpu")
    t0 = time.perf_counter()
    res_fast = sess.query("SELECT count(*) AS c FROM demodata")
    fast_t = time.perf_counter() - t0
    res_full = sess.query("SELECT count(*) AS c FROM demodata WHERE bytes >= 0")
    fast_count = res_fast.to_json_rows()[0]["c"]
    full_count = res_full.to_json_rows()[0]["c"]
    ok = res_fast.stats.get("fast_path") == "manifest_count" and fast_count == full_count
    if not ok:
        print(
            f"# WARNING config1 fast path mismatch: fast={fast_count} "
            f"({res_fast.stats.get('fast_path')}) full={full_count}",
            file=sys.stderr,
        )
    emit(
        "config1_manifest_count_latency_ms",
        fast_t * 1000,
        1.0,
        {
            "unit": "ms",
            "validated_vs_full_scan": ok,
            "count": fast_count,
            "note": "count(*) off manifest row counts, no scan",
        },
    )


def bench_scale_subprocess(with_tpu: bool) -> None:
    """Config 4 at 100GB-logical scale over the persistent .benchwork
    dataset (scripts/bench_scale.py; VERDICT r4 #2). Runs only when the
    dataset has been built (scripts/build_benchwork.py); the TPU engine
    uses the real chip when reachable, else a virtual 8-device CPU mesh —
    either way the full tiering (hot set under eviction pressure +
    enccache) is the thing under test. BENCH_SCALE=0 skips; the timeout
    (BENCH_SCALE_TIMEOUT, default 2700s) bounds the driver's bench run."""
    here = os.path.dirname(os.path.abspath(__file__))
    if os.environ.get("BENCH_SCALE", "1") == "0":
        return
    if not os.path.exists(os.path.join(here, ".benchwork", "meta.json")):
        print("# scale bench: no .benchwork dataset (scripts/build_benchwork.py)", file=sys.stderr)
        return
    if with_tpu:
        # IN-PROCESS on the real chip: libtpu holds an exclusive device
        # lock, so a --real subprocess could never initialize while this
        # process owns the chip
        try:
            sys.path.insert(0, os.path.join(here, "scripts"))
            import bench_scale

            bench_scale.main(real=True)
        except Exception as e:  # noqa: BLE001
            print(f"# scale bench failed: {e}", file=sys.stderr)
        return
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        out = subprocess.run(
            [sys.executable, "scripts/bench_scale.py"],
            capture_output=True,
            text=True,
            timeout=int(os.environ.get("BENCH_SCALE_TIMEOUT", "2700")),
            env=env,
            cwd=here,
        )
        for line in out.stdout.strip().splitlines():
            print(f"# scale: {line}", file=sys.stderr)
        lines = out.stdout.strip().splitlines()
        if out.returncode != 0 or not lines:
            print(
                f"# scale bench rc={out.returncode}; stderr: {out.stderr[-2000:]}",
                file=sys.stderr,
            )
            return
        last = json.loads(lines[-1])
        if last.get("metric"):
            print(json.dumps(last), flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"# scale bench failed: {e}", file=sys.stderr)


def bench_json_ingest(p) -> None:
    """End-to-end HTTP JSON ingest line with an honest absolute yardstick
    (VERDICT r3 #7): vs_baseline is measured against the raw pyarrow C++
    JSON-reader floor over the SAME payload bytes — the fastest any
    Python-hosted server could conceivably decode it with a reader, with
    zero event model, schema commit, or staging. The native columnar lane
    (fastpath.cpp single-pass parse -> Arrow-layout buffers -> zero-copy
    import) runs the whole pipeline and can legitimately EXCEED 1.0x: it
    parses the bytes once into final columns while read_json tokenizes
    into its own intermediate representation first."""
    import io as _io

    import numpy as np
    import pyarrow.json as pj

    from parseable_tpu.event.format import LogSource
    from parseable_tpu.server.ingest_utils import flatten_and_push_logs

    rng = np.random.default_rng(7)
    n = 100_000
    chunk = 10_000
    rows = [
        {
            "host": f"h{i % 50}",
            "status": int(rng.integers(200, 600)),
            "method": "GET",
            "path": f"/api/v{i % 5}/items",
            "latency_ms": float(rng.random() * 500),
            "meta": {"region": f"r{i % 4}", "zone": f"z{i % 3}"},
        }
        for i in range(n)
    ]
    bodies = [
        json.dumps(rows[o : o + chunk]).encode() for o in range(0, n, chunk)
    ]
    # the floor parses the same records as NDJSON (read_json's wire
    # format; feeding it the HTTP array body would error)
    floor_bodies = [
        ("\n".join(json.dumps(r) for r in rows[o : o + chunk]) + "\n").encode()
        for o in range(0, n, chunk)
    ]
    p.create_stream_if_not_exists("ingbench")
    # warm both paths (library load, stream schema commit, reader import)
    flatten_and_push_logs(p, "ingbench", None, LogSource.JSON, {}, raw_body=bodies[0])
    pj.read_json(_io.BytesIO(floor_bodies[0]))

    # p50/p95 over reps for BOTH lines — the repo's bench policy (PR 2)
    # bans best-of: a best-of hides the tail variance the latency north
    # star exists to capture, and it biased this line's vs_baseline
    reps = max(3, int(os.environ.get("BENCH_REPEATS", "3")))
    cores = os.cpu_count() or 1
    shards_n = min(cores, 4)
    payload_gb = sum(len(b) for b in bodies) / 1e9

    def run_ours(shards: int, telem: bool = True) -> list[float]:
        # pin the shard count (and drop the byte threshold so every chunk
        # actually shards) for the duration of the measured loop; telem=False
        # A/Bs the native telemetry plane off (read per-call via telem_sync)
        os.environ["P_INGEST_PARSE_SHARDS"] = str(shards)
        os.environ["P_INGEST_SHARD_MIN_BYTES"] = "0"
        if not telem:
            os.environ["P_NATIVE_TELEM"] = "0"
        try:
            times: list[float] = []
            for _ in range(reps):
                t0 = time.perf_counter()
                for b in bodies:
                    flatten_and_push_logs(
                        p, "ingbench", None, LogSource.JSON, {}, raw_body=b
                    )
                times.append(time.perf_counter() - t0)
            return times
        finally:
            os.environ.pop("P_INGEST_PARSE_SHARDS", None)
            os.environ.pop("P_INGEST_SHARD_MIN_BYTES", None)
            os.environ.pop("P_NATIVE_TELEM", None)

    def stage_sums() -> dict[str, float]:
        # cumulative ingest_stage_seconds sums per stage (lanes folded in),
        # read through the public collect() API — deltas around a measured
        # run give the per-stage waterfall attribution for that run
        from parseable_tpu.utils.metrics import INGEST_STAGE_TIME

        out: dict[str, float] = {}
        for metric in INGEST_STAGE_TIME.collect():
            for s in metric.samples:
                if s.name.endswith("_sum"):
                    stage = s.labels["stage"]
                    out[stage] = out.get(stage, 0.0) + s.value
        return out

    pre = stage_sums()
    shard1_times = run_ours(1)
    mid = stage_sums()
    ours_times = run_ours(shards_n) if shards_n > 1 else shard1_times
    post = stage_sums()
    # attribute stages to the headline run (which is the shard1 run itself
    # on a 1-core box, where no second measured loop happens)
    lo, hi = (mid, post) if shards_n > 1 else (pre, mid)
    stage_ms = {
        k: (hi.get(k, 0.0) - lo.get(k, 0.0)) * 1e3 / reps
        for k in sorted(set(lo) | set(hi))
    }
    teloff_times = run_ours(shards_n, telem=False)
    ours = n / percentile(ours_times, 0.50)
    shard1 = n / percentile(shard1_times, 0.50)
    teloff = n / percentile(teloff_times, 0.50)
    # telemetry cost = slowdown of the telemetry-ON run vs OFF (<1 means
    # noise put the ON run ahead; the gate only cares about the upper side)
    telem_overhead_pct = (teloff / ours - 1.0) * 100.0

    floor_times: list[float] = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for b in floor_bodies:
            pj.read_json(_io.BytesIO(b))
        floor_times.append(time.perf_counter() - t0)
    floor = n / percentile(floor_times, 0.50)
    gb_per_sec = payload_gb / percentile(ours_times, 0.50)
    print(
        f"# json ingest: {ours:,.0f} rows/s end-to-end (p50; p95 "
        f"{n / percentile(ours_times, 0.95):,.0f}) | pyarrow floor {floor:,.0f} rows/s | "
        f"{ours / floor:.2f}x of floor | {gb_per_sec:.3f} GB/s",
        file=sys.stderr,
    )
    print(
        f"# json ingest sharding: shards=1 {shard1:,.0f} rows/s vs "
        f"shards={shards_n} {ours:,.0f} rows/s ({ours / shard1:.2f}x on a "
        f"{cores}-core box; {ours / shards_n:,.0f} rows/s/core)",
        file=sys.stderr,
    )
    breakdown = " | ".join(f"{k} {v:.1f}ms" for k, v in stage_ms.items() if v > 0)
    print(
        f"# json ingest stages (per rep, {n:,} rows): {breakdown or 'n/a'} | "
        f"telemetry off {teloff:,.0f} rows/s (on-cost "
        f"{telem_overhead_pct:+.1f}%)",
        file=sys.stderr,
    )
    emit(
        "http_json_ingest_rows_per_sec",
        round(ours, 1),
        round(ours / floor, 4),
        {
            "note": (
                "full pipeline (sharded single-pass C++ columnar build -> "
                "ordered stitch -> zero-copy Arrow import -> schema/staging "
                "with direct-to-IPC; NDJSON+read_json as the fallback tier) "
                "vs raw pyarrow read_json floor on the same bytes; p50 over "
                "reps, never best-of"
            ),
            "repeats": reps,
            "latency_p50_s": round(percentile(ours_times, 0.50), 4),
            "latency_p95_s": round(percentile(ours_times, 0.95), 4),
            "pyarrow_floor_rows_per_sec": round(floor, 1),
            "pyarrow_floor_p50_s": round(percentile(floor_times, 0.50), 4),
            "pyarrow_floor_p95_s": round(percentile(floor_times, 0.95), 4),
            "gb_per_sec": round(gb_per_sec, 4),
            "rows_per_sec_per_core": round(ours / shards_n, 1),
            "cores": cores,
            "parse_shards": shards_n,
            "shards1_rows_per_sec": round(shard1, 1),
            "shard_scaling_x": round(ours / shard1, 4),
            "stage_ms_per_rep": {k: round(v, 2) for k, v in stage_ms.items()},
            "telem_off_rows_per_sec": round(teloff, 1),
            "telem_overhead_pct": round(telem_overhead_pct, 2),
        },
    )


def bench_edge() -> None:
    """Native HTTP ingest edge (fastpath.cpp acceptor, PR "zero-Python
    happy path") vs the aiohttp tier of the SAME server process, measured
    wrk-style over loopback: persistent keep-alive connections, a fixed
    offered load (rows/s; 0 = saturate), identical payload bytes on both
    ports. Reports GB/s, rows/s-per-core and p50/p95/p99 ack latency next
    to the in-process bench_json_ingest lines. vs_baseline = edge rows/s /
    aiohttp rows/s (the PR's acceptance bar is >= 1.5x). Passes interleave
    edge/aiohttp (A/B/A/B...) inside one server boot and the reported rate
    is the p50 across passes — host-load drift on a shared box would
    otherwise swing the ratio by +/-0.2x. Env knobs: BENCH_EDGE (0 skips),
    BENCH_EDGE_CONNS (4; 1 on a single-core host, where the co-located
    client's extra threads only time-slice the server's CPU and the run
    measures scheduler fairness instead of the server), BENCH_EDGE_REQS
    (300 per tier per pass), BENCH_EDGE_BATCH (200 rows per request),
    BENCH_EDGE_OFFERED_ROWS (0 = unthrottled), BENCH_REPEATS (3 passes
    per tier)."""
    import pathlib
    import socket as socketmod
    import threading

    if os.environ.get("BENCH_EDGE", "1") == "0":
        return
    here = os.path.dirname(os.path.abspath(__file__))
    scripts_dir = os.path.join(here, "scripts")
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    from blackbox import AUTH_HEADER, ClusterHarness, free_port

    default_conns = 1 if (os.cpu_count() or 1) == 1 else 4
    conns = int(os.environ.get("BENCH_EDGE_CONNS", str(default_conns)))
    n_reqs = int(os.environ.get("BENCH_EDGE_REQS", "300"))
    batch = int(os.environ.get("BENCH_EDGE_BATCH", "200"))
    offered = float(os.environ.get("BENCH_EDGE_OFFERED_ROWS", "0"))
    cores = os.cpu_count() or 1

    rng = np.random.default_rng(17)
    rows = [
        {
            "host": f"h{i % 50}",
            "status": int(rng.integers(200, 600)),
            "method": "GET",
            "path": f"/api/v{i % 5}/items",
            "latency_ms": float(rng.random() * 500),
            "meta": {"region": f"r{i % 4}", "zone": f"z{i % 3}"},
        }
        for i in range(batch * 8)
    ]
    # a small pool of distinct bodies reused round-robin — prebuilt so the
    # measured loop never json.dumps under the GIL the server also needs
    bodies = [
        json.dumps(rows[o : o + batch]).encode()
        for o in range(0, len(rows), batch)
    ]
    bytes_per_req = sum(len(b) for b in bodies) / len(bodies)

    def build_reqs(port: int, stream: str) -> list[bytes]:
        out = []
        for b in bodies:
            head = (
                f"POST /api/v1/ingest HTTP/1.1\r\n"
                f"Host: 127.0.0.1:{port}\r\n"
                f"Authorization: {AUTH_HEADER['Authorization']}\r\n"
                f"Content-Type: application/json\r\n"
                f"X-P-Stream: {stream}\r\n"
                f"Content-Length: {len(b)}\r\n\r\n"
            ).encode()
            out.append(head + b)
        return out

    def read_ack(sock, buf: bytes) -> tuple[int, bytes]:
        # both tiers answer this route Content-Length-framed
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(65536)
            if not chunk:
                raise RuntimeError("connection closed mid-response")
            buf += chunk
        head, _, rest = buf.partition(b"\r\n\r\n")
        status = int(head.split(None, 2)[1])
        cl = 0
        for line in head.split(b"\r\n")[1:]:
            k, _, v = line.partition(b":")
            if k.strip().lower() == b"content-length":
                cl = int(v.strip())
        while len(rest) < cl:
            chunk = sock.recv(65536)
            if not chunk:
                raise RuntimeError("connection closed mid-body")
            rest += chunk
        return status, rest[cl:]

    def drive(port: int, reqs: list[bytes]) -> dict:
        """One measured pass: `conns` persistent connections, requests
        paced on a single global open-loop schedule (behind-schedule sends
        go immediately, so overload shows up in the ack latencies)."""
        interval = (batch / offered) if offered > 0 else 0.0
        results: list[dict] = [dict() for _ in range(conns)]
        barrier = threading.Barrier(conns + 1)

        def sender(slot: int) -> None:
            sock = socketmod.create_connection(("127.0.0.1", port), timeout=60)
            sock.setsockopt(socketmod.IPPROTO_TCP, socketmod.TCP_NODELAY, 1)
            lats: list[float] = []
            acked = sent_bytes = 0
            buf = b""
            try:
                barrier.wait()
                t_base = t_start[0]
                first = last = None
                for i in range(slot, n_reqs, conns):
                    if interval:
                        tgt = t_base + i * interval
                        now = time.perf_counter()
                        if now < tgt:
                            time.sleep(tgt - now)
                    t0 = time.perf_counter()
                    req = reqs[i % len(reqs)]
                    sock.sendall(req)
                    status, buf = read_ack(sock, buf)
                    t1 = time.perf_counter()
                    if status != 200:
                        raise RuntimeError(f"ack status {status}")
                    lats.append(t1 - t0)
                    acked += batch
                    sent_bytes += len(req)
                    first = t0 if first is None else first
                    last = t1
                results[slot] = {
                    "lats": lats,
                    "acked": acked,
                    "bytes": sent_bytes,
                    "first": first,
                    "last": last,
                }
            finally:
                sock.close()

        threads = [
            threading.Thread(target=sender, args=(s,), daemon=True)
            for s in range(conns)
        ]
        t_start = [0.0]
        for t in threads:
            t.start()
        t_start[0] = time.perf_counter() + 0.05  # common schedule origin
        barrier.wait()
        for t in threads:
            t.join(600)
        done = [r for r in results if r.get("acked")]
        if not done:
            raise RuntimeError("no sender completed")
        wall = max(r["last"] for r in done) - min(r["first"] for r in done)
        acked = sum(r["acked"] for r in done)
        return {
            "rows_per_sec": acked / wall,
            "gb_per_sec": sum(r["bytes"] for r in done) / wall / 1e9,
            "lats_ms": [x * 1e3 for r in done for x in r["lats"]],
            "acked_rows": acked,
            "wall_s": wall,
        }

    workdir = tempfile.mkdtemp(prefix="ptpu-edgebench-")
    try:
        edge_port = free_port()
        with ClusterHarness(pathlib.Path(workdir)) as cluster:
            node = cluster.spawn(
                "all",
                "edgebench",
                env_extra={
                    "P_EDGE_PORT": str(edge_port),
                    # keep the sync loop out of the measured window; the
                    # ~120k rows staged here sit comfortably in the arena
                    "P_LOCAL_SYNC_INTERVAL": "3600",
                },
            )
            cluster.wait_live(node)
            try:
                probe = socketmod.create_connection(("127.0.0.1", edge_port), 5)
                probe.close()
            except OSError:
                print(
                    "# edge bench skipped: native edge acceptor not listening "
                    "(library without ptpu_edge_* or start failure)",
                    file=sys.stderr,
                )
                return

            tiers = {
                "edge": (edge_port, build_reqs(edge_port, "ebench")),
                "aiohttp": (node.port, build_reqs(node.port, "ebench")),
            }
            # warm both tiers on the SAME stream first (stream creation +
            # schema commit are one-time costs, not per-tier differences)
            warm_sock = socketmod.create_connection(("127.0.0.1", edge_port), 30)
            wbuf = b""
            for _ in range(3):
                warm_sock.sendall(tiers["edge"][1][0])
                status, wbuf = read_ack(warm_sock, wbuf)
                assert status == 200, f"edge warmup ack {status}"
            warm_sock.close()
            warm_sock = socketmod.create_connection(("127.0.0.1", node.port), 30)
            wbuf = b""
            for _ in range(3):
                warm_sock.sendall(tiers["aiohttp"][1][0])
                status, wbuf = read_ack(warm_sock, wbuf)
                assert status == 200, f"aiohttp warmup ack {status}"
            warm_sock.close()

            reps = max(1, int(os.environ.get("BENCH_REPEATS", "3")))
            passes: dict[str, list[dict]] = {name: [] for name in tiers}
            for _ in range(reps):
                for name, (port, reqs) in tiers.items():
                    passes[name].append(drive(port, reqs))
            stats = {}
            for name, runs in passes.items():
                lats_ms = sorted(
                    x for r in runs for x in r["lats_ms"]
                )
                stats[name] = {
                    "rows_per_sec": percentile(
                        [r["rows_per_sec"] for r in runs], 0.50
                    ),
                    "gb_per_sec": percentile(
                        [r["gb_per_sec"] for r in runs], 0.50
                    ),
                    "p50_ms": percentile(lats_ms, 0.50),
                    "p95_ms": percentile(lats_ms, 0.95),
                    "p99_ms": percentile(lats_ms, 0.99),
                    "acked_rows": sum(r["acked_rows"] for r in runs),
                    "wall_s": sum(r["wall_s"] for r in runs),
                }

            edge_counters = {}
            try:
                report = cluster.audit(node, scope="local", quiesce=False)
                edge_counters = report.get("edge") or {}
            except Exception as e:  # noqa: BLE001 - bench-only extra
                print(f"# edge bench: audit probe failed: {e}", file=sys.stderr)

        e, a = stats["edge"], stats["aiohttp"]
        speedup = e["rows_per_sec"] / max(a["rows_per_sec"], 1e-9)
        for name, s in stats.items():
            print(
                f"# edge bench [{name}]: {s['rows_per_sec']:,.0f} rows/s "
                f"({s['gb_per_sec']:.3f} GB/s, {s['rows_per_sec']/cores:,.0f} "
                f"rows/s/core) | ack p50 {s['p50_ms']:.1f}ms p95 "
                f"{s['p95_ms']:.1f}ms p99 {s['p99_ms']:.1f}ms | "
                f"{s['acked_rows']} rows over {conns} conns in {s['wall_s']:.2f}s",
                file=sys.stderr,
            )
        print(
            f"# edge bench: native edge {speedup:.2f}x aiohttp rows/s at equal "
            f"payloads ({batch} rows/req, ~{bytes_per_req/1e3:.1f}KB bodies, "
            f"{'unthrottled' if not offered else f'{offered:,.0f} rows/s offered'})",
            file=sys.stderr,
        )
        emit(
            "edge_native_ingest_rows_per_sec",
            e["rows_per_sec"],
            speedup,
            {
                "note": (
                    "C++ epoll acceptor (socket->shard arena, zero Python "
                    "objects on the happy path) vs the aiohttp tier of the "
                    "same process; persistent keep-alive conns over "
                    "loopback, identical payload bytes, open-loop schedule"
                ),
                "conns": conns,
                "requests_per_tier": n_reqs,
                "batch_rows": batch,
                "body_bytes_avg": round(bytes_per_req, 1),
                "offered_rows_per_sec": offered or "unthrottled",
                "cores": cores,
                "gb_per_sec": round(e["gb_per_sec"], 4),
                "rows_per_sec_per_core": round(e["rows_per_sec"] / cores, 1),
                "latency_p50_ms": round(e["p50_ms"], 2),
                "latency_p95_ms": round(e["p95_ms"], 2),
                "latency_p99_ms": round(e["p99_ms"], 2),
                "aiohttp_rows_per_sec": round(a["rows_per_sec"], 1),
                "aiohttp_gb_per_sec": round(a["gb_per_sec"], 4),
                "aiohttp_rows_per_sec_per_core": round(a["rows_per_sec"] / cores, 1),
                "aiohttp_latency_p50_ms": round(a["p50_ms"], 2),
                "aiohttp_latency_p95_ms": round(a["p95_ms"], 2),
                "aiohttp_latency_p99_ms": round(a["p99_ms"], 2),
                "edge_counters": edge_counters,
            },
        )
    except Exception as exc:  # noqa: BLE001
        print(f"# edge bench failed: {exc}", file=sys.stderr)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_ingest_pipeline() -> None:
    """Write-path benchmark (parallel write path PR): N streams of backdated
    minute buckets, measuring staging->queryable latency (flush -> compact ->
    upload -> snapshot commit, per stream) and sync-path rows/s — serial
    baseline (P_SYNC_WORKERS=1, two-phase local_sync + upload tick) vs the
    pooled pipelined sync_cycle. Pure host work; runs with or without the
    chip. Env knobs: BENCH_INGEST_STREAMS (6), BENCH_INGEST_ROWS (100000
    rows per stream)."""
    import pathlib

    from parseable_tpu import DEFAULT_TIMESTAMP_KEY
    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable
    from parseable_tpu.event import Event

    n_streams = int(os.environ.get("BENCH_INGEST_STREAMS", "8"))
    rows_per_stream = int(os.environ.get("BENCH_INGEST_ROWS", "60000"))
    # pooled workers: at least 4 even on small hosts — parquet encode
    # releases the GIL and the uploads are I/O, so overlap pays regardless
    pooled_workers = int(
        os.environ.get("BENCH_INGEST_WORKERS", str(max(4, Options().sync_workers)))
    )
    # model the remote object store: each upload pays one simulated RTT so
    # the serial-vs-pipelined difference reflects the deployment the write
    # path actually targets (set 0 to measure raw local-fs copies)
    upload_ms = float(os.environ.get("BENCH_INGEST_UPLOAD_MS", "25"))
    minutes = 4
    base = datetime(2024, 5, 1, 0, 0, tzinfo=UTC)

    def run_mode(mode: str) -> dict:
        rng = np.random.default_rng(11)
        workdir = tempfile.mkdtemp(prefix=f"ptpu-ingbench-{mode}-")
        opts = Options()
        opts.local_staging_path = pathlib.Path(workdir) / "staging"
        opts.sync_workers = 1 if mode == "serial" else pooled_workers
        storage = StorageOptions(
            backend="local-store", root=pathlib.Path(workdir) / "data"
        )
        p = Parseable(opts, storage)
        if upload_ms > 0:
            real_upload = p.storage.upload_file

            def slow_upload(key, path):
                time.sleep(upload_ms / 1000.0)
                return real_upload(key, path)

            p.storage.upload_file = slow_upload
        try:
            per_minute = max(1, rows_per_stream // minutes)
            for si in range(n_streams):
                name = f"ing{si}"
                stream = p.create_stream_if_not_exists(name)
                for minute in range(minutes):
                    ts = [
                        base + timedelta(minutes=minute, milliseconds=int(o))
                        for o in np.sort(rng.integers(0, 60_000, per_minute))
                    ]
                    tbl = pa.table(
                        {
                            DEFAULT_TIMESTAMP_KEY: pa.array(
                                [t.replace(tzinfo=None) for t in ts], pa.timestamp("ms")
                            ),
                            "host": pa.array([f"h{i % 32}" for i in range(per_minute)]),
                            "status": pa.array(rng.choice([200.0, 404.0, 500.0], per_minute)),
                            "bytes": pa.array(rng.random(per_minute) * 1000),
                        }
                    ).combine_chunks()
                    for batch in tbl.to_batches():
                        Event(
                            stream_name=name,
                            rb=batch,
                            origin_size=batch.num_rows * 100,
                            is_first_event=minute == 0,
                            parsed_timestamp=base + timedelta(minutes=minute),
                        ).process(stream, commit_schema=p.commit_schema)
            # per-stream visibility instant = its snapshot commit landing
            commit_times: dict[str, float] = {}
            orig_update = p.update_snapshot

            def timed_update(stream, entries):
                orig_update(stream, entries)
                commit_times[stream.name] = time.perf_counter()

            p.update_snapshot = timed_update
            t0 = time.perf_counter()
            if mode == "serial":
                p.local_sync(shutdown=True)
                p.sync_all_streams()
            else:
                p.sync_cycle(shutdown=True)
            total = time.perf_counter() - t0
            p.update_snapshot = orig_update
            lats = sorted(
                commit_times.get(f"ing{si}", t0 + total) - t0 for si in range(n_streams)
            )
            p.shutdown()
            return {
                "total_s": total,
                "lat_p50_s": percentile(lats, 0.50),
                "lat_p95_s": percentile(lats, 0.95),
                "rows_per_sec": n_streams * per_minute * minutes / total,
            }
        finally:
            shutil.rmtree(workdir, ignore_errors=True)

    serial = run_mode("serial")
    pooled = run_mode("pooled")
    speedup = serial["total_s"] / max(pooled["total_s"], 1e-9)
    print(
        f"# ingest sync: serial {serial['total_s']:.3f}s "
        f"(lat p50 {serial['lat_p50_s']:.3f}s p95 {serial['lat_p95_s']:.3f}s) | "
        f"pooled {pooled['total_s']:.3f}s "
        f"(lat p50 {pooled['lat_p50_s']:.3f}s p95 {pooled['lat_p95_s']:.3f}s) | "
        f"{speedup:.2f}x",
        file=sys.stderr,
    )
    emit(
        "ingest_sync_rows_per_sec",
        pooled["rows_per_sec"],
        speedup,
        {
            "streams": n_streams,
            "rows_per_stream": rows_per_stream,
            "sync_workers": pooled_workers,
            "upload_rtt_ms": upload_ms,
            "serial_total_s": round(serial["total_s"], 4),
            "pooled_total_s": round(pooled["total_s"], 4),
            "serial_lat_p50_s": round(serial["lat_p50_s"], 4),
            "serial_lat_p95_s": round(serial["lat_p95_s"], 4),
            "pooled_lat_p50_s": round(pooled["lat_p50_s"], 4),
            "pooled_lat_p95_s": round(pooled["lat_p95_s"], 4),
            "note": (
                "staging->queryable (flush+compact+upload+commit) across N "
                "streams; serial = P_SYNC_WORKERS=1 two-phase ticks, pooled "
                "= pipelined sync_cycle on the shared sync pool"
            ),
        },
    )


def bench_query_concurrency() -> None:
    """Closed-loop concurrent query serving bench (the BASELINE.md latency
    north star no bench emitted before this): N concurrent clients — one
    heavy full-range aggregate, the rest light dashboard-style narrow-range
    aggregates — against one node with background ingest running, under a
    simulated object-store GET RTT so scan tasks have real service time.

    Phase 1/2 A/B the shared scan scheduler's dispatch policy (fifo vs
    fair) with the result cache OFF and report the light-query p50/p95/p99
    per policy: fair round-robin must beat global FIFO at the tail, because
    the heavy scan's backlog no longer sits in front of every dashboard
    query. Phase 3 turns the partial-aggregate result cache on and measures
    the same heavy aggregate cold vs warm (warm must skip the scan).

    Env knobs: BENCH_QC_CLIENTS (8), BENCH_QC_SECS (6 per policy phase),
    BENCH_QC_FILES (24 manifest files), BENCH_QC_ROWS (4000 rows/file),
    BENCH_QC_GET_MS (10 ms simulated GET RTT), BENCH_QC_SCAN_WORKERS (2).
    """
    import pathlib
    import threading

    from parseable_tpu import DEFAULT_TIMESTAMP_KEY
    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable
    from parseable_tpu.event import Event
    from parseable_tpu.query.provider import get_scan_scheduler
    from parseable_tpu.query.session import QuerySession

    n_clients = int(os.environ.get("BENCH_QC_CLIENTS", "8"))
    phase_secs = float(os.environ.get("BENCH_QC_SECS", "6"))
    n_files = int(os.environ.get("BENCH_QC_FILES", "24"))
    rows_per_file = int(os.environ.get("BENCH_QC_ROWS", "4000"))
    get_ms = float(os.environ.get("BENCH_QC_GET_MS", "10"))
    base = datetime(2024, 5, 1, 0, 0, tzinfo=UTC)
    hist = ("2024-05-01T00:00:00Z", "2024-05-02T00:00:00Z")
    # 3 of the N files: the dashboard query a heavy scan must not starve
    light_range = ("2024-05-01T00:01:00Z", "2024-05-01T00:04:00Z")

    workdir = tempfile.mkdtemp(prefix="ptpu-qcbench-")
    try:
        opts = Options()
        opts.local_staging_path = pathlib.Path(workdir) / "staging"
        opts.scan_workers = int(os.environ.get("BENCH_QC_SCAN_WORKERS", "2"))
        opts.query_result_cache_bytes = 0  # phases 1-2 measure scheduling
        storage = StorageOptions(
            backend="local-store", root=pathlib.Path(workdir) / "data"
        )
        p = Parseable(opts, storage)
        rng = np.random.default_rng(17)
        stream = p.create_stream_if_not_exists("qc")
        for minute in range(n_files):
            n = rows_per_file
            ts = [
                base + timedelta(minutes=minute, milliseconds=int(o))
                for o in np.sort(rng.integers(0, 60_000, n))
            ]
            tbl = pa.table(
                {
                    DEFAULT_TIMESTAMP_KEY: pa.array(
                        [t.replace(tzinfo=None) for t in ts], pa.timestamp("ms")
                    ),
                    "host": pa.array([f"h{i % 16}" for i in range(n)]),
                    "status": pa.array(
                        rng.choice([200.0, 404.0, 500.0], n).astype(np.float64)
                    ),
                    "bytes": pa.array(rng.random(n) * 1000),
                }
            ).combine_chunks()
            for batch in tbl.to_batches():
                Event(
                    stream_name="qc",
                    rb=batch,
                    origin_size=batch.num_rows * 100,
                    is_first_event=minute == 0,
                    parsed_timestamp=base + timedelta(minutes=minute),
                ).process(stream, commit_schema=p.commit_schema)
        p.local_sync(shutdown=True)
        p.sync_all_streams()

        # simulated object-store RTT: without it, local-fs reads finish so
        # fast the dispatch policy can't matter
        real_get = p.storage.get_object

        def slow_get(key):
            time.sleep(get_ms / 1000.0)
            return real_get(key)

        p.storage.get_object = slow_get

        heavy_sql = (
            "SELECT host, status, count(*) c, sum(bytes) s FROM qc "
            "GROUP BY host, status"
        )
        light_sql = "SELECT host, count(*) c FROM qc GROUP BY host"

        def one(sql, rng_pair):
            return QuerySession(p, engine="cpu").query(sql, *rng_pair)

        # warm the plan cache + code paths so neither phase pays first-run
        one(heavy_sql, hist)
        one(light_sql, light_range)

        def run_phase(policy: str) -> dict:
            opts.scan_sched = policy
            get_scan_scheduler(opts)  # re-root onto the policy under test
            lats: list[float] = []
            llock = threading.Lock()
            stop = threading.Event()
            errors: list[str] = []
            heavy_done = [0]

            def heavy_client():
                while not stop.is_set():
                    try:
                        one(heavy_sql, hist)
                        heavy_done[0] += 1
                    except Exception as e:  # noqa: BLE001 - recorded
                        errors.append(repr(e))
                        return

            def light_client():
                while not stop.is_set():
                    t0 = time.perf_counter()
                    try:
                        one(light_sql, light_range)
                    except Exception as e:  # noqa: BLE001 - recorded
                        errors.append(repr(e))
                        return
                    with llock:
                        lats.append(time.perf_counter() - t0)

            def ingest_client():
                # background ingest: staging writes racing the queries
                i = 0
                while not stop.is_set():
                    n = 500
                    tbl = pa.table(
                        {
                            DEFAULT_TIMESTAMP_KEY: pa.array(
                                [
                                    (base + timedelta(hours=2, seconds=i * 60 + k)).replace(
                                        tzinfo=None
                                    )
                                    for k in range(n)
                                ],
                                pa.timestamp("ms"),
                            ),
                            "host": pa.array(["ing"] * n),
                            "status": pa.array([200.0] * n),
                            "bytes": pa.array([1.0] * n),
                        }
                    )
                    for batch in tbl.to_batches():
                        Event(
                            stream_name="qc", rb=batch, origin_size=n * 100,
                            is_first_event=False,
                            parsed_timestamp=base + timedelta(hours=2),
                        ).process(stream, commit_schema=p.commit_schema)
                    i += 1
                    time.sleep(0.05)

            threads = [threading.Thread(target=heavy_client)]
            threads += [
                threading.Thread(target=light_client) for _ in range(n_clients - 1)
            ]
            threads += [threading.Thread(target=ingest_client)]
            for t in threads:
                t.start()
            time.sleep(phase_secs)
            stop.set()
            for t in threads:
                t.join()
            if errors:
                print(f"# qc bench [{policy}] errors: {errors[:3]}", file=sys.stderr)
            return {
                "n": len(lats),
                "p50": percentile(lats, 0.50),
                "p95": percentile(lats, 0.95),
                "p99": percentile(lats, 0.99),
                "heavy_done": heavy_done[0],
            }

        fifo = run_phase("fifo")
        fair = run_phase("fair")

        # phase 3: partial-aggregate result cache, cold vs warm repeat
        opts.query_result_cache_bytes = 64 * 1024 * 1024
        t0 = time.perf_counter()
        cold_res = one(heavy_sql, hist)
        cold_s = time.perf_counter() - t0
        warm_s = 1e9
        warm_hit = False
        for _ in range(3):
            t0 = time.perf_counter()
            warm_res = one(heavy_sql, hist)
            warm_s = min(warm_s, time.perf_counter() - t0)
            warm_hit = warm_hit or (
                warm_res.stats["stages"].get("result_cache") == "hit"
            )
        ratio = warm_s / max(cold_s, 1e-9)
        assert cold_res.table.num_rows == warm_res.table.num_rows

        speedup_p95 = fifo["p95"] / max(fair["p95"], 1e-9)
        print(
            f"# query concurrency ({n_clients} clients + ingest, {n_files} files, "
            f"{get_ms:.0f}ms GET): light fifo p50 {fifo['p50']*1e3:.0f}ms "
            f"p95 {fifo['p95']*1e3:.0f}ms p99 {fifo['p99']*1e3:.0f}ms | "
            f"fair p50 {fair['p50']*1e3:.0f}ms p95 {fair['p95']*1e3:.0f}ms "
            f"p99 {fair['p99']*1e3:.0f}ms ({speedup_p95:.2f}x p95) | "
            f"agg cache cold {cold_s*1e3:.0f}ms warm {warm_s*1e3:.0f}ms "
            f"({ratio:.3f}x, hit={warm_hit})",
            file=sys.stderr,
        )
        emit(
            "bench_query_concurrency",
            fair["n"] / max(phase_secs, 1e-9),
            speedup_p95,
            {
                "unit": "queries/s",
                "clients": n_clients,
                "phase_secs": phase_secs,
                "files": n_files,
                "sim_get_ms": get_ms,
                "scan_workers": opts.scan_workers,
                "background_ingest": True,
                "light_p50_s_fair": round(fair["p50"], 4),
                "light_p95_s_fair": round(fair["p95"], 4),
                "light_p99_s_fair": round(fair["p99"], 4),
                "light_p50_s_fifo": round(fifo["p50"], 4),
                "light_p95_s_fifo": round(fifo["p95"], 4),
                "light_p99_s_fifo": round(fifo["p99"], 4),
                "light_queries_fair": fair["n"],
                "light_queries_fifo": fifo["n"],
                "heavy_queries_fair": fair["heavy_done"],
                "heavy_queries_fifo": fifo["heavy_done"],
                "fair_vs_fifo_p95": round(speedup_p95, 3),
                "agg_cache_cold_s": round(cold_s, 4),
                "agg_cache_warm_s": round(warm_s, 4),
                "agg_cache_warm_over_cold": round(ratio, 4),
                "agg_cache_hit": warm_hit,
                "note": (
                    "closed-loop light-query latency under one heavy scan + "
                    "background ingest; fair = per-query weighted RR on the "
                    "shared scan pool, fifo = global arrival order; cache = "
                    "partial-aggregate result cache cold vs warm repeat"
                ),
            },
        )
        p.shutdown()
    except Exception as e:  # noqa: BLE001
        print(f"# query concurrency bench failed: {e}", file=sys.stderr)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_memory_pressure(emit_line: bool = True) -> dict | None:
    """Tiering under real memory pressure (ROADMAP "make the tiering story
    true"): a high-entropy dataset split across many parquet files, queried
    warm with P_TPU_HOT_BYTES capped WELL below the encoded working set, so
    every repetition pays eviction + re-ship for the part that doesn't fit.
    A/Bs the eviction policy (P_TPU_HOT_POLICY=cost vs lru) over >=10 warm
    reps and reports p50/p95 per policy plus the tiering counters — done
    means hotset_evictions > 0 while the cost-policy warm ratio still beats
    the CPU engine.

    Under pressure LRU is pathological for a cyclic warm scan (each rep
    flushes exactly the blocks the next rep needs first); the cost policy's
    frequency x ship-cost scoring + probationary segment converges on a
    stable resident subset, and the query-aware prefetcher overlaps the
    re-ship of the rest with device compute.

    Like bench_query_concurrency / bench_ingest_pipeline, the deployment's
    I/O costs are simulated so a local-fs dev box measures the path the
    design targets: every storage GET pays BENCH_MP_GET_MS (the CPU engine
    re-fetches parquet from the object store every rep) and every enccache
    block load pays BENCH_MP_SHIP_MS (the tier's local re-ship: NVMe read +
    PCIe put — cheaper than a remote GET, which is exactly why the tier
    exists). Prefetch overlaps the re-ship with compute; protected hot-set
    hits skip it entirely.

    Env knobs: BENCH_MP_FILES (12), BENCH_MP_FILE_ROWS (100000),
    BENCH_MP_REPEATS (10), BENCH_MP_BUDGET_FRAC (0.35 of the measured
    working set), BENCH_MP_GET_MS (25), BENCH_MP_SHIP_MS (10). Pure
    in-process work; runs with or without the real chip (tier-1 smokes it
    with tiny knobs so the eviction path can never rot into dead code
    again)."""
    import pathlib

    from parseable_tpu import DEFAULT_TIMESTAMP_KEY
    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable
    from parseable_tpu.event import Event
    from parseable_tpu.ops.enccache import get_enccache
    from parseable_tpu.ops.hotset import get_hotset
    from parseable_tpu.query.session import QuerySession

    n_files = int(os.environ.get("BENCH_MP_FILES", "12"))
    rows_per_file = int(os.environ.get("BENCH_MP_FILE_ROWS", "100000"))
    repeats = int(os.environ.get("BENCH_MP_REPEATS", "10"))
    budget_frac = float(os.environ.get("BENCH_MP_BUDGET_FRAC", "0.35"))
    get_ms = float(os.environ.get("BENCH_MP_GET_MS", "25"))
    ship_ms = float(os.environ.get("BENCH_MP_SHIP_MS", "10"))
    rows_total = n_files * rows_per_file
    base = datetime(2024, 5, 1, 0, 0, tzinfo=UTC)
    sql = (
        "SELECT path, host, count(*) c, sum(bytes) s FROM mp "
        "GROUP BY path, host"
    )

    saved_env = {
        k: os.environ.get(k) for k in ("P_TPU_HOT_BYTES", "P_TPU_HOT_POLICY")
    }
    workdir = tempfile.mkdtemp(prefix="ptpu-mpbench-")
    summary: dict | None = None
    unpatch: list = []  # (obj, attr, original) — the enccache is process-global
    try:
        opts = Options()
        opts.local_staging_path = pathlib.Path(workdir) / "staging"
        storage = StorageOptions(
            backend="local-store", root=pathlib.Path(workdir) / "data"
        )
        p = Parseable(opts, storage)
        rng = np.random.default_rng(23)
        stream = p.create_stream_if_not_exists("mp")
        n_hosts = int(os.environ.get("BENCH_MP_HOSTS", "32"))
        hosts = [f"10.0.{i // 16}.{i % 16}" for i in range(n_hosts)]
        paths = [f"/api/v1/resource{i}" for i in range(64)]
        for minute in range(n_files):
            n = rows_per_file
            ts = [
                base + timedelta(minutes=minute, milliseconds=int(o))
                for o in np.sort(rng.integers(0, 60_000, n))
            ]
            tbl = pa.table(
                {
                    DEFAULT_TIMESTAMP_KEY: pa.array(
                        [t.replace(tzinfo=None) for t in ts], pa.timestamp("ms")
                    ),
                    "host": pa.array(np.array(hosts)[rng.integers(0, len(hosts), n)]),
                    "path": pa.array(np.array(paths)[rng.integers(0, len(paths), n)]),
                    # high-entropy payload: full-mantissa uniform floats and
                    # per-row-unique messages — parquet compression buys
                    # ~nothing, disk size ~= logical size
                    "bytes": pa.array((rng.random(n) * 50_000).astype(np.float64)),
                    "message": pa.array(
                        [f"request {minute * n + i} completed" for i in range(n)]
                    ),
                }
            ).combine_chunks()
            for batch in tbl.to_batches():
                Event(
                    stream_name="mp",
                    rb=batch,
                    origin_size=batch.num_rows * 100,
                    is_first_event=minute == 0,
                    parsed_timestamp=base + timedelta(minutes=minute),
                ).process(stream, commit_schema=p.commit_schema)
        p.local_sync(shutdown=True)
        p.sync_all_streams()

        # simulated deployment I/O: object-store GET RTT on the storage
        # client (paid by anything re-reading parquet) and a local re-ship
        # latency on enccache block loads (the tier's miss cost)
        if get_ms > 0:
            real_get_object = p.storage.get_object
            real_get_range = p.storage.get_range

            def slow_get_object(key):
                time.sleep(get_ms / 1000.0)
                return real_get_object(key)

            def slow_get_range(key, start, end):
                time.sleep(get_ms / 1000.0)
                return real_get_range(key, start, end)

            p.storage.get_object = slow_get_object
            p.storage.get_range = slow_get_range

        cpu = timed_runs(p, "mp", "cpu", sql, max(2, min(repeats, 3)))

        def run_tpu() -> tuple[float, dict]:
            t0 = time.perf_counter()
            res = QuerySession(p, engine="tpu").query(sql)
            return time.perf_counter() - t0, res.stats

        # phase 0: all-resident pass under the default (huge) budget to
        # measure the encoded working set and seed the enccache
        os.environ.pop("P_TPU_HOT_BYTES", None)
        os.environ["P_TPU_HOT_POLICY"] = "cost"
        hs = get_hotset()
        hs.clear()
        run_tpu()
        working_set = hs.resident_bytes
        ec = get_enccache(p.options)
        if ec is not None:
            ec.wait_idle()
            if ship_ms > 0:
                real_ec_get = ec.get

                def slow_ec_get(source_id, needed, dict_cols):
                    time.sleep(ship_ms / 1000.0)
                    return real_ec_get(source_id, needed, dict_cols)

                ec.get = slow_ec_get
                unpatch.append((ec, "get", real_ec_get))
        budget = max(1, int(working_set * budget_frac))
        os.environ["P_TPU_HOT_BYTES"] = str(budget)

        phases: dict[str, dict] = {}
        for policy in ("lru", "cost"):
            os.environ["P_TPU_HOT_POLICY"] = policy
            hs = get_hotset()  # re-roots onto the capped budget + policy
            hs.clear()
            run_tpu()  # populate up to the capped budget
            ev0, times, last_stats = hs.evictions, [], {}
            for _ in range(max(1, repeats)):
                dt, last_stats = run_tpu()
                times.append(dt)
            stages = (last_stats.get("stages") or {}).get("hotset") or {}
            phases[policy] = {
                "p50": percentile(times, 0.50),
                "p95": percentile(times, 0.95),
                "evictions": hs.evictions - ev0,
                "resident_bytes": hs.resident_bytes,
                "prefetch_issued": stages.get("prefetch_issued", 0),
                "prefetch_hits": stages.get("prefetch_hits", 0),
                "prefetch_wasted": stages.get("prefetch_wasted", 0),
            }

        import jax

        cost, lru = phases["cost"], phases["lru"]
        cpus = (
            len(os.sched_getaffinity(0))
            if hasattr(os, "sched_getaffinity")
            else (os.cpu_count() or 1)
        )
        summary = {
            "files": n_files,
            "rows": rows_total,
            "repeats": repeats,
            "profile": "highentropy",
            "sim_get_ms": get_ms,
            "sim_ship_ms": ship_ms,
            "platform": jax.devices()[0].platform,
            "cpus": cpus,
            "working_set_bytes": working_set,
            "hot_budget_bytes": budget,
            "hotset_evictions": cost["evictions"],
            "hotset_evictions_lru": lru["evictions"],
            "warm_p50_s_cost": round(cost["p50"], 4),
            "warm_p95_s_cost": round(cost["p95"], 4),
            "warm_p50_s_lru": round(lru["p50"], 4),
            "warm_p95_s_lru": round(lru["p95"], 4),
            "cost_vs_lru_p95": round(lru["p95"] / max(cost["p95"], 1e-9), 3),
            "cpu_p50_s": round(cpu["p50"], 4),
            "warm_vs_cpu": round(cpu["p50"] / max(cost["p50"], 1e-9), 3),
            "prefetch_issued": cost["prefetch_issued"],
            "prefetch_hits": cost["prefetch_hits"],
            "prefetch_wasted": cost["prefetch_wasted"],
            "enccache_dropped": getattr(ec, "dropped", 0) if ec else 0,
            "note": (
                "warm reps with P_TPU_HOT_BYTES capped below the encoded "
                "working set over a high-entropy profile; cost = freq x "
                "recency x re-ship-cost eviction + probation + prefetch, "
                "lru = plain LRU A/B"
            ),
        }
        print(
            f"# memory pressure ({n_files} files, ws {working_set/1e6:.1f}MB, "
            f"budget {budget/1e6:.1f}MB): cost p50 {cost['p50']*1e3:.0f}ms "
            f"p95 {cost['p95']*1e3:.0f}ms ({cost['evictions']} evictions, "
            f"{cost['prefetch_hits']}/{cost['prefetch_issued']} prefetch hits) | "
            f"lru p50 {lru['p50']*1e3:.0f}ms p95 {lru['p95']*1e3:.0f}ms "
            f"({lru['evictions']} evictions) | cpu p50 {cpu['p50']*1e3:.0f}ms",
            file=sys.stderr,
        )
        if emit_line:
            emit(
                "bench_memory_pressure",
                rows_total / max(cost["p50"], 1e-9),
                cpu["p50"] / max(cost["p50"], 1e-9),
                summary,
            )
        p.shutdown()
    except Exception as e:  # noqa: BLE001
        print(f"# memory pressure bench failed: {e}", file=sys.stderr)
    finally:
        for obj, attr, orig in unpatch:
            setattr(obj, attr, orig)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        get_hotset().clear()  # drop capped-budget state for later phases
        shutil.rmtree(workdir, ignore_errors=True)
    return summary


def _flight_fanin_ab(workdir, reps: int, stream: str) -> dict | None:
    """Interleaved Flight-vs-HTTP fan-in A/B over the live ingestor
    processes: one in-process QUERY-mode client against the harness's
    shared store pulls `stream`'s staging window over each transport rung
    back-to-back, alternating the order per pair. The caller loads the
    window once into quiescent (sync-paused) ingestors, so every pull
    sees the byte-identical, cache-hot window — the A/B measures the
    wire, not the server-side window build. Returns per-transport GB/s +
    per-pull wire bytes, or None if the A/B could not run at all."""
    from parseable_tpu.config import Mode, Options, StorageOptions
    from parseable_tpu.core import Parseable
    from parseable_tpu.server import cluster as C

    opts = Options()
    opts.mode = Mode.QUERY
    opts.local_staging_path = workdir / "staging-ab"
    q = Parseable(
        opts, StorageOptions(backend="local-store", root=workdir / "shared-store")
    )
    sides: dict = {
        t: {"secs": [], "bytes": [], "fallbacks": 0} for t in ("flight", "http")
    }

    def pull(transport: str) -> None:
        q.options.flight_client = transport == "flight"
        st: dict = {}
        t0 = time.perf_counter()
        C.fetch_staging_batches(q, stream, stats=st)
        side = sides[transport]
        side["secs"].append(time.perf_counter() - t0)
        side["bytes"].append(st.get("bytes", 0))
        side["fallbacks"] += st.get("flight_fallbacks", 0)

    try:
        # warm both rungs: channel dial / keep-alive socket, and the
        # server-side cold window build lands here instead of in a sample
        for t in ("flight", "http", "flight", "http"):
            pull(t)
        for side in sides.values():
            side["secs"].clear()
            side["bytes"].clear()
            side["fallbacks"] = 0
        for i in range(reps):
            order = ("flight", "http") if i % 2 == 0 else ("http", "flight")
            for t in order:
                pull(t)
    except Exception as e:  # noqa: BLE001 - bench-only
        print(f"# flight fan-in A/B failed: {e}", file=sys.stderr)
        return None
    finally:
        q.shutdown()
        C.shutdown_flight_pool()
        C.shutdown_conn_pool()
        C.shutdown_cluster_pool()

    out: dict = {}
    for t, side in sides.items():
        total_b, total_s = sum(side["bytes"]), sum(side["secs"])
        out[t] = {
            "gbs": total_b / max(total_s, 1e-9) / 1e9,
            "p50_s": percentile(side["secs"], 0.50),
            "wire_bytes_per_pull": total_b / max(1, len(side["bytes"])),
            "flight_fallbacks": side["fallbacks"],
        }
    return out


def bench_distributed_fanout() -> None:
    """Distributed fan-out bench with a REAL multi-process baseline
    (ROADMAP: "give the distributed mesh bench a real baseline ... not
    vs_baseline: 1.0"): scripts/blackbox.py boots 1 querier per data plane
    + N ingestor processes over a shared LocalFS store, sustains background
    ingest, and replays a dashboard-style GROUP BY aggregate over the last
    minutes against both planes:

    - central pull (P_QUERY_PUSHDOWN=0): the querier pulls every peer's
      staging window over Arrow IPC and scans all parquet itself;
    - pushdown (default): peers execute scan + partial aggregation on
      node-local data and ship one partial table each.

    Reports p50/p95 client-side latency and BYTES OVER THE WIRE (the
    querier<->ingestor data plane: raw staging IPC vs partial tables) per
    query, p50/p95 over BENCH_DF_QUERIES reps. vs_baseline = central p95 /
    pushdown p95. A second record, bench_flight_fanin, comes from an
    interleaved Flight-vs-HTTP staging fan-in A/B against the same live
    ingestors (GB/s + per-pull wire bytes per transport). Env knobs:
    BENCH_DF (0 skips), BENCH_DF_INGESTORS (2), BENCH_DF_QUERIES (12),
    BENCH_DF_PRELOAD_ROWS (24000 per ingestor), BENCH_DF_INGEST_ROWS
    (400 per background tick), BENCH_DF_AB_ROWS (960000 once per A/B
    ingestor — ~20MB windows, big enough that the wire dominates the
    per-pull fixed costs)."""
    import pathlib
    import threading

    if os.environ.get("BENCH_DF", "1") == "0":
        return
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "scripts"))
    from blackbox import ClusterHarness

    n_ing = int(os.environ.get("BENCH_DF_INGESTORS", "2"))
    n_queries = int(os.environ.get("BENCH_DF_QUERIES", "12"))
    preload = int(os.environ.get("BENCH_DF_PRELOAD_ROWS", "200000"))
    bg_rows = int(os.environ.get("BENCH_DF_INGEST_ROWS", "1000"))
    workdir = tempfile.mkdtemp(prefix="ptpu-dfbench-")
    sql = "SELECT host, count(*) c, sum(v) s, avg(v) a FROM dfb GROUP BY host"
    rng = np.random.default_rng(31)

    def batch(n: int) -> list[dict]:
        return [
            {"host": f"h{int(i) % 16}", "v": float(v)}
            for i, v in zip(rng.integers(0, 1 << 30, n), rng.random(n) * 100)
        ]

    try:
        with ClusterHarness(pathlib.Path(workdir)) as cluster:
            # sync fast so preloaded rows reach manifests while background
            # ingest keeps a live staging window on every peer
            ing_env = {"P_LOCAL_SYNC_INTERVAL": "3", "P_STORAGE_UPLOAD_INTERVAL": "2"}
            # flight=True: ingestors serve both data-plane tiers, so the
            # queriers ride the Arrow Flight hot tier by default and the
            # A/B below can pin P_FLIGHT_CLIENT per pull
            ingestors = [
                cluster.spawn("ingest", f"ing{i}", env_extra=ing_env, flight=True)
                for i in range(n_ing)
            ]
            q_central = cluster.spawn(
                "query", "q-central", env_extra={"P_QUERY_PUSHDOWN": "0"}
            )
            q_push = cluster.spawn(
                "query", "q-push", env_extra={"P_QUERY_PUSHDOWN": "1"}
            )
            for node in [*ingestors, q_central, q_push]:
                cluster.wait_live(node)

            t0 = time.perf_counter()
            for node in ingestors:
                done = 0
                while done < preload:
                    k = min(4000, preload - done)
                    cluster.ingest(node, "dfb", batch(k))
                    done += k
            print(
                f"# fanout bench: {n_ing}x{preload} rows preloaded in "
                f"{time.perf_counter() - t0:.1f}s",
                file=sys.stderr,
            )
            time.sleep(6)  # one sync tick: most of the preload reaches manifests

            stop = threading.Event()

            def background_ingest():
                while not stop.is_set():
                    for node in ingestors:
                        try:
                            cluster.ingest(node, "dfb", batch(bg_rows))
                        except Exception as e:  # noqa: BLE001 - bench-only
                            print(f"# bg ingest failed: {e}", file=sys.stderr)
                            return
                    stop.wait(0.25)

            bg = threading.Thread(target=background_ingest, daemon=True)
            bg.start()

            def phase(node) -> dict:
                cluster.query(node, sql, "5m", "now")  # warm plan/stream load
                lats, wire, push_ok, fallbacks, flight_n = [], [], 0, 0, 0
                for _ in range(n_queries):
                    t0 = time.perf_counter()
                    records, stats = cluster.query(node, sql, "5m", "now")
                    lats.append(time.perf_counter() - t0)
                    fan = (stats.get("stages") or {}).get("fanout") or {}
                    wire.append(
                        fan.get("bytes", 0) + fan.get("fanin_bytes", 0)
                    )
                    push_ok += fan.get("ok", 0)
                    fallbacks += fan.get("fallback", 0)
                    # pushdown scatter reports {"flight": n}; the central
                    # plane's staging fan-in reports {"flight_peers": n}
                    t = fan.get("transport", {})
                    flight_n += t.get("flight", 0) + t.get("flight_peers", 0)
                    assert records, "dashboard aggregate returned no groups"
                return {
                    "p50": percentile(lats, 0.50),
                    "p95": percentile(lats, 0.95),
                    "wire_bytes_per_query": sum(wire) / max(1, len(wire)),
                    "pushdown_ok": push_ok,
                    "fallbacks": fallbacks,
                    "flight_peers": flight_n,
                }

            central = phase(q_central)
            push = phase(q_push)
            stop.set()
            bg.join(10)

            # Flight-vs-HTTP fan-in A/B: one in-process QUERY-mode client
            # alternating the transport pull-by-pull, measuring raw
            # data-plane GB/s. Dedicated ingestors with sync paused hold a
            # frozen window, so every pull ships the byte-identical,
            # cache-hot payload — the A/B measures the wire, not the
            # server-side window build (the main-phase ingestors answer
            # this stream with an empty window on both rungs alike).
            ab_rows = int(os.environ.get("BENCH_DF_AB_ROWS", "960000"))
            ab_env = {
                "P_LOCAL_SYNC_INTERVAL": "3600",
                "P_STORAGE_UPLOAD_INTERVAL": "3600",
            }
            ab_ing = [
                cluster.spawn("ingest", f"ab{i}", env_extra=ab_env, flight=True)
                for i in range(n_ing)
            ]
            for node in ab_ing:
                cluster.wait_live(node)
            for node in ab_ing:
                done = 0
                while done < ab_rows:
                    k = min(4000, ab_rows - done)
                    cluster.ingest(node, "dfab", batch(k))
                    done += k
            ab = _flight_fanin_ab(pathlib.Path(workdir), n_queries, "dfab")

        byte_reduction = central["wire_bytes_per_query"] / max(
            1.0, push["wire_bytes_per_query"]
        )
        p95_speedup = central["p95"] / max(push["p95"], 1e-9)
        print(
            f"# distributed fanout ({n_ing} ingestors + 2 queriers, background "
            f"ingest): central p50 {central['p50']*1e3:.0f}ms p95 "
            f"{central['p95']*1e3:.0f}ms {central['wire_bytes_per_query']/1e3:.1f}KB/q | "
            f"pushdown p50 {push['p50']*1e3:.0f}ms p95 {push['p95']*1e3:.0f}ms "
            f"{push['wire_bytes_per_query']/1e3:.1f}KB/q | {p95_speedup:.2f}x p95, "
            f"{byte_reduction:.1f}x fewer bytes",
            file=sys.stderr,
        )
        emit(
            "bench_distributed_fanout",
            1.0 / max(push["p50"], 1e-9),
            p95_speedup,
            {
                "unit": "queries/s",
                "processes": n_ing + 2,
                "ingestors": n_ing,
                "queries_per_phase": n_queries,
                "background_ingest": True,
                "central_p50_s": round(central["p50"], 4),
                "central_p95_s": round(central["p95"], 4),
                "pushdown_p50_s": round(push["p50"], 4),
                "pushdown_p95_s": round(push["p95"], 4),
                "central_wire_bytes_per_query": round(central["wire_bytes_per_query"], 1),
                "pushdown_wire_bytes_per_query": round(push["wire_bytes_per_query"], 1),
                "wire_byte_reduction": round(byte_reduction, 2),
                "pushdown_ok_total": push["pushdown_ok"],
                "pushdown_fallbacks": push["fallbacks"],
                "pushdown_flight_peers": push["flight_peers"],
                "central_flight_peers": central["flight_peers"],
                "note": (
                    "1 querier per data plane + N ingestor PROCESSES over "
                    "LocalFS (scripts/blackbox.py) under sustained ingest; "
                    "dashboard GROUP BY over the last 5 minutes; central = "
                    "raw staging pull + full local scan, pushdown = per-peer "
                    "partial aggregation; wire bytes = querier<->ingestor "
                    "data plane only; both queriers ride the Arrow Flight "
                    "hot tier (flight_peers counts per-peer Flight wins)"
                ),
            },
        )
        if ab and ab["flight"]["wire_bytes_per_pull"] > 0 and ab["http"]["gbs"] > 0:
            fanin_speedup = ab["flight"]["gbs"] / max(ab["http"]["gbs"], 1e-9)
            print(
                f"# flight fan-in A/B: flight {ab['flight']['gbs']:.3f} GB/s "
                f"({ab['flight']['wire_bytes_per_pull'] / 1e6:.2f} MB/pull) vs "
                f"http {ab['http']['gbs']:.3f} GB/s "
                f"({ab['http']['wire_bytes_per_pull'] / 1e6:.2f} MB/pull) -> "
                f"{fanin_speedup:.2f}x fan-in throughput",
                file=sys.stderr,
            )
            emit(
                "bench_flight_fanin",
                ab["flight"]["gbs"],
                fanin_speedup,
                {
                    "unit": "GB/s",
                    "ingestors": n_ing,
                    "ab_pairs": n_queries,
                    "ab_rows_per_ingestor": ab_rows,
                    "flight_gbs": round(ab["flight"]["gbs"], 4),
                    "http_gbs": round(ab["http"]["gbs"], 4),
                    "flight_p50_s": round(ab["flight"]["p50_s"], 4),
                    "http_p50_s": round(ab["http"]["p50_s"], 4),
                    "flight_wire_bytes_per_pull": round(
                        ab["flight"]["wire_bytes_per_pull"], 1
                    ),
                    "http_wire_bytes_per_pull": round(
                        ab["http"]["wire_bytes_per_pull"], 1
                    ),
                    "flight_fallbacks": ab["flight"]["flight_fallbacks"],
                    "note": (
                        "interleaved A/B, one in-process QUERY client vs the "
                        "live ingestor processes: staging-window fan-in over "
                        "Arrow Flight vs keep-alive HTTP+IPC, every peer's "
                        "window refilled before each pair so payloads match "
                        "and the pull order alternates; GB/s = wire bytes / "
                        "wall time per transport"
                    ),
                },
            )
    except Exception as e:  # noqa: BLE001
        print(f"# distributed fanout bench failed: {e}", file=sys.stderr)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_otel_ingest(p) -> None:
    """OTel-logs ingest line: the native C++ lane (fastpath.cpp walk ->
    NDJSON -> pyarrow reader -> staging) vs the Python flattener pipeline
    over the same bytes, both end-to-end through flatten_and_push_logs
    (VERDICT r4 #3: >=200k rows/s). Pure host work — runs whether or not
    the chip is reachable."""

    n_groups, n_recs = 10, 2000
    rls = []
    for g in range(n_groups):
        recs = []
        for i in range(n_recs):
            recs.append(
                {
                    "timeUnixNano": str(1714521600000000000 + i * 1_000_000),
                    "observedTimeUnixNano": str(1714521600500000000 + i * 1_000_000),
                    "severityNumber": 9 + (i % 4),
                    "body": {"stringValue": f"request {i} completed"},
                    "attributes": [
                        {"key": "http.status_code", "value": {"intValue": str(200 + i % 4)}},
                        {"key": "http.method", "value": {"stringValue": "GET"}},
                    ],
                    "traceId": f"{i:032x}",
                    "spanId": f"{i:016x}",
                }
            )
        rls.append(
            {
                "resource": {
                    "attributes": [
                        {"key": "service.name", "value": {"stringValue": f"svc{g}"}}
                    ]
                },
                "scopeLogs": [{"scope": {"name": "app"}, "logRecords": recs}],
            }
        )
    payload = {"resourceLogs": rls}
    body = json.dumps(payload).encode()
    total = n_groups * n_recs

    p.create_stream_if_not_exists("otelbench")

    from parseable_tpu.event.format import LogSource
    from parseable_tpu.server.ingest_utils import flatten_and_push_logs

    def ingest_native(shards: int) -> float:
        os.environ["P_INGEST_PARSE_SHARDS"] = str(shards)
        os.environ["P_INGEST_SHARD_MIN_BYTES"] = "0"
        try:
            t0 = time.perf_counter()
            n = flatten_and_push_logs(
                p, "otelbench", None, LogSource.OTEL_LOGS, {}, raw_body=body
            )
            assert n == total
            return time.perf_counter() - t0
        finally:
            os.environ.pop("P_INGEST_PARSE_SHARDS", None)
            os.environ.pop("P_INGEST_SHARD_MIN_BYTES", None)

    def ingest_python() -> float:
        # the exact-semantics fallback pipeline over the same bytes
        t0 = time.perf_counter()
        n = flatten_and_push_logs(
            p, "otelbench", json.loads(body), LogSource.OTEL_LOGS, {}
        )
        assert n == total
        return time.perf_counter() - t0

    cores = os.cpu_count() or 1
    shards_n = min(cores, 4)
    ingest_native(1)  # warm (library load, stream schema, reader import)
    fast_times = [ingest_native(shards_n) for _ in range(3)]
    t_fast = percentile(fast_times, 0.50)
    t_fast_p95 = percentile(fast_times, 0.95)
    t_1 = percentile([ingest_native(1) for _ in range(3)], 0.50) if shards_n > 1 else t_fast
    t_py = min(ingest_python() for _ in range(2))
    gb_per_sec = len(body) / 1e9 / t_fast
    print(
        f"# otel ingest: native {t_fast:.3f}s ({total/t_fast:,.0f} r/s, "
        f"{gb_per_sec:.3f} GB/s) | python {t_py:.3f}s ({total/t_py:,.0f} r/s) | "
        f"{t_py/t_fast:.1f}x",
        file=sys.stderr,
    )
    print(
        f"# otel ingest sharding: shards=1 {total/t_1:,.0f} r/s vs "
        f"shards={shards_n} {total/t_fast:,.0f} r/s ({t_1/t_fast:.2f}x on a "
        f"{cores}-core box; {total/t_fast/shards_n:,.0f} r/s/core)",
        file=sys.stderr,
    )
    emit(
        "otel_logs_ingest_rows_per_sec",
        total / t_fast,
        t_py / t_fast,
        {
            "note": "native C++ columnar OTel lane (sharded single-pass -> Arrow buffers -> ordered stitch) vs Python flattener pipeline, end-to-end incl. staging",
            "latency_p50_s": round(t_fast, 4),
            "latency_p95_s": round(t_fast_p95, 4),
            "gb_per_sec": round(gb_per_sec, 4),
            "rows_per_sec_per_core": round(total / t_fast / shards_n, 1),
            "cores": cores,
            "parse_shards": shards_n,
            "shards1_rows_per_sec": round(total / t_1, 1),
            "shard_scaling_x": round(t_1 / t_fast, 4),
        },
    )


def tpu_available(timeout_secs: float = 90.0) -> bool:
    """Probe the device with a timeout: a wedged tunnel must produce a
    recorded result, not a killed silent bench."""
    import threading

    result: list = []

    def probe():
        try:
            import jax

            devs = jax.devices()
            import jax.numpy as jnp

            jnp.ones(8).sum().block_until_ready()
            result.append(devs)
        except Exception as e:  # noqa: BLE001
            result.append(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_secs)
    if not result or isinstance(result[0], Exception):
        print(f"# TPU probe failed: {result[0] if result else 'timeout'}", file=sys.stderr)
        return False
    print(f"# devices: {result[0]}", file=sys.stderr)
    return True


def main() -> None:
    total_rows = int(os.environ.get("BENCH_ROWS", "32000000"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))

    if not tpu_available():
        # the host-only lines still measure without the chip: emit the
        # unreachable marker first, then OTel ingest + the virtual-mesh
        # distributed number last (the driver records the final line)
        emit(
            "tpu_unreachable",
            0.0,
            0.0,
            {"note": "device probe timed out (tunnel down); TPU configs skipped"},
        )
        workdir = tempfile.mkdtemp(prefix="ptpu-bench-")
        try:
            from parseable_tpu.config import Options, StorageOptions
            from parseable_tpu.core import Parseable

            opts = Options()
            opts.local_staging_path = __import__("pathlib").Path(workdir) / "staging"
            storage = StorageOptions(
                backend="local-store", root=__import__("pathlib").Path(workdir) / "data"
            )
            pb = Parseable(opts, storage)
            bench_otel_ingest(pb)
            bench_json_ingest(pb)
            bench_edge()
            bench_ingest_pipeline()
            bench_query_concurrency()
            bench_distributed_fanout()
            bench_memory_pressure()
            bench_config1(pb, with_tpu=False)
            bench_scale_subprocess(with_tpu=False)
        except Exception as e:  # noqa: BLE001
            print(f"# ingest bench failed: {e}", file=sys.stderr)
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
        bench_distributed_subprocess(total_rows)
        return

    workdir = tempfile.mkdtemp(prefix="ptpu-bench-")
    try:
        from parseable_tpu.config import Options, StorageOptions
        from parseable_tpu.core import Parseable

        opts = Options()
        opts.local_staging_path = __import__("pathlib").Path(workdir) / "staging"
        storage = StorageOptions(backend="local-store", root=__import__("pathlib").Path(workdir) / "data")
        p = Parseable(opts, storage)

        t0 = time.perf_counter()
        build_dataset(p, "bench", total_rows)
        print(f"# dataset: {total_rows} rows built+cataloged in {time.perf_counter()-t0:.1f}s", file=sys.stderr)

        # characterize the link once so cold numbers are interpretable
        # (tunneled dev chips have wildly asymmetric transfer profiles)
        try:
            import jax
            import numpy as _np

            x = _np.random.rand(16 << 18).astype(_np.float32)  # 16 MB
            jax.device_put(x[:1024]).block_until_ready()
            t1 = time.perf_counter()
            dev = jax.device_put(x)
            dev.block_until_ready()
            h2d = x.nbytes / (time.perf_counter() - t1)
            small = jax.device_put(_np.ones(64_000, _np.float32))
            small.block_until_ready()
            t1 = time.perf_counter()
            _np.asarray(small)
            d2h_lat = time.perf_counter() - t1
            print(
                f"# link: h2d {h2d/1e6:.0f} MB/s (16MB put), d2h 256KB in {d2h_lat*1e3:.0f}ms",
                file=sys.stderr,
            )
            emit(
                "link_h2d_bytes_per_sec",
                h2d,
                1.0,
                {"d2h_256k_secs": round(d2h_lat, 3), "note": "link characterization"},
            )
        except Exception as e:  # noqa: BLE001
            print(f"# link characterization failed: {e}", file=sys.stderr)

        # measure + EMIT each config as it completes (a killed run still
        # records whatever finished); the north-star config runs last so
        # its line stays the final one when everything completes
        def measure_and_emit(name: str, sql: str, stream: str = "bench") -> None:
            from parseable_tpu.ops.enccache import get_enccache
            from parseable_tpu.query import executor_tpu as ET

            cpu = timed_runs(p, stream, "cpu", sql, max(1, repeats - 1))
            cpu_t, rows, cpu_rows = cpu["p50"], cpu["rows_scanned"], cpu["rows"]
            # compile first (one-time XLA cost), THEN measure cold: the cold
            # number is the data path (parquet fetch + decode + transfer +
            # compute, overlapped by the parallel scan pool), not compilation
            run_query(p, stream, "tpu", sql)
            # let write-behind land: cold must measure the disk-cache path,
            # not a race with the enccache writer
            ec = get_enccache(p.options)
            if ec is not None:
                ec.wait_idle()
            # cold = the disk-cache/data path with no device-resident blocks,
            # re-cleared before every repeat so it too gets p50/p95
            adaptive_before = ET.ADAPTIVE_CPU_BLOCKS[0]
            cold_times: list[float] = []
            cold_stats: dict = {}
            for _ in range(max(1, repeats - 1)):
                clear_hot_state()
                dt, _, _, cold_stats = run_query(p, stream, "tpu", sql)
                cold_times.append(dt)
            cold_t = percentile(cold_times, 0.50)
            cold_p95 = percentile(cold_times, 0.95)
            cold_adaptive = ET.ADAPTIVE_CPU_BLOCKS[0] - adaptive_before
            warm = timed_runs(p, stream, "tpu", sql, repeats)
            warm_t, tpu_rows = warm["p50"], warm["rows"]
            if not rows_match(cpu_rows, tpu_rows):
                print(f"# WARNING: {name} results differ!", file=sys.stderr)
                print(f"#   cpu: {cpu_rows[:2]} tpu: {tpu_rows[:2]}", file=sys.stderr)
            print(
                f"# {name}: cpu p50 {cpu_t:.3f}s | tpu cold p50 {cold_t:.3f}s "
                f"p95 {cold_p95:.3f}s ({rows/cold_t:,.0f} r/s, {cpu_t/cold_t:.1f}x, "
                f"{cold_stats.get('bytes_scanned', 0)/1e6:.1f} MB fetched) | "
                f"tpu warm p50 {warm_t:.3f}s p95 {warm['p95']:.3f}s "
                f"({rows/warm_t:,.0f} r/s, {cpu_t/warm_t:.1f}x)",
                file=sys.stderr,
            )
            metric = (
                "topk_multicol_groupby_rows_per_sec_tpu"
                if name == "topk_multicol"
                else f"{name}_scan_rows_per_sec_tpu"
            )
            extra = {
                "repeats": repeats,
                "warm_p50_s": round(warm_t, 4),
                "warm_p95_s": round(warm["p95"], 4),
                "cpu_p50_s": round(cpu_t, 4),
                "cpu_p95_s": round(cpu["p95"], 4),
                "cold_rows_per_sec": round(rows / cold_t, 1),
                "cold_vs_baseline": round(cpu_t / cold_t, 3),
                "cold_p50_s": round(cold_t, 4),
                "cold_p95_s": round(cold_p95, 4),
                # cold-scan fetch accounting: the projected range reads'
                # win shows up here as fetched bytes < dataset bytes
                "cold_bytes_scanned": cold_stats.get("bytes_scanned", 0),
                "cold_bytes_saved_by_projection": cold_stats.get(
                    "bytes_saved_by_projection", 0
                ),
            }
            if cold_adaptive:
                # the measured link made shipping a losing trade for some
                # cold blocks: they aggregated host-side while the device
                # warmed in the background (ops/link.py)
                extra["cold_adaptive_cpu_blocks"] = cold_adaptive
            emit(metric, rows / warm_t, cpu_t / warm_t, extra)

        for name, sql in CONFIGS.items():
            if name != "topk_multicol":
                measure_and_emit(name, sql)
        bench_distributed_subprocess(total_rows)
        bench_otel_ingest(p)
        bench_json_ingest(p)
        bench_edge()
        bench_ingest_pipeline()
        bench_query_concurrency()
        bench_distributed_fanout()
        bench_memory_pressure()
        bench_config1(p, with_tpu=True)
        bench_scale_subprocess(with_tpu=True)

        # high-cardinality profile (VERDICT r2 "de-rig"): same configs 3-4
        # over ~10k hosts / ~100k paths / ~50k-unique-per-block messages —
        # the regressions this exposes are honest work, not hidden
        hc_rows = int(os.environ.get("BENCH_HC_ROWS", str(max(total_rows // 4, 1_000_000))))
        t0 = time.perf_counter()
        build_dataset(p, "bench_hc", hc_rows, profile="highcard")
        print(
            f"# highcard dataset: {hc_rows} rows built in {time.perf_counter()-t0:.1f}s",
            file=sys.stderr,
        )
        measure_and_emit("regex_filter_highcard", CONFIGS["regex_filter"], stream="bench_hc")
        measure_and_emit("topk_multicol_highcard", CONFIGS["topk_multicol"], stream="bench_hc")

        # north star LAST (config 4)
        measure_and_emit("topk_multicol", CONFIGS["topk_multicol"])
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    main()
